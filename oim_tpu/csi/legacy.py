"""CSI 0.3 legacy personality: v0 servicers wrapping the v1 servers.

≙ reference pkg/oim-csi-driver/{driver0.go,identityserver0.go,
controllerserver0.go,nodeserver0.go}: ``oimDriver03`` embeds ``oimDriver``
and re-implements the service surface against the vendored CSI 0.3
protobuf.  Same shape here: each v0 servicer holds the v1 servicer and
translates requests/replies at the boundary — the volume logic (backends,
mounter, rendezvous, keymutex) runs once, in the v1 code.

Translation notes (proto/csi/v0/csi.proto documents the wire deltas):
- ``VolumeCapability``/``Topology`` are wire-identical across versions, so
  they recode via serialize→parse.
- v0 ``Volume.id/attributes`` ↔ v1 ``volume_id/volume_context``.
- v0 ``ValidateVolumeCapabilities`` returns a bare ``supported`` bool.
- v0 ``NodeGetId`` has no v1 counterpart; it answers from the node server.
"""

from __future__ import annotations

from oim_tpu.spec import csi0_pb2, csi_pb2


def _recode(msg, target_cls):
    """Re-type a wire-identical message across proto packages."""
    return target_cls.FromString(msg.SerializeToString())


def _recode_all(msgs, target_cls):
    return [_recode(m, target_cls) for m in msgs]


class IdentityServer0:
    def __init__(self, identity) -> None:
        self.v1 = identity

    def GetPluginInfo(self, request, context) -> csi0_pb2.GetPluginInfoResponse:
        reply = self.v1.GetPluginInfo(csi_pb2.GetPluginInfoRequest(), context)
        out = csi0_pb2.GetPluginInfoResponse(
            name=reply.name, vendor_version=reply.vendor_version
        )
        out.manifest.update(reply.manifest)
        return out

    def GetPluginCapabilities(
        self, request, context
    ) -> csi0_pb2.GetPluginCapabilitiesResponse:
        reply = self.v1.GetPluginCapabilities(
            csi_pb2.GetPluginCapabilitiesRequest(), context
        )
        out = csi0_pb2.GetPluginCapabilitiesResponse()
        for cap in reply.capabilities:
            # Service capability types share numbering (v1's
            # VOLUME_ACCESSIBILITY_CONSTRAINTS = v0's
            # ACCESSIBILITY_CONSTRAINTS = 2).
            out.capabilities.add().service.type = cap.service.type
        return out

    def Probe(self, request, context) -> csi0_pb2.ProbeResponse:
        reply = self.v1.Probe(csi_pb2.ProbeRequest(), context)
        out = csi0_pb2.ProbeResponse()
        out.ready.value = reply.ready.value
        return out


class ControllerServer0:
    def __init__(self, controller) -> None:
        self.v1 = controller

    def CreateVolume(self, request, context) -> csi0_pb2.CreateVolumeResponse:
        req = csi_pb2.CreateVolumeRequest(
            name=request.name,
            volume_capabilities=_recode_all(
                request.volume_capabilities, csi_pb2.VolumeCapability
            ),
        )
        req.capacity_range.required_bytes = request.capacity_range.required_bytes
        req.capacity_range.limit_bytes = request.capacity_range.limit_bytes
        req.parameters.update(request.parameters)
        reply = self.v1.CreateVolume(req, context)
        out = csi0_pb2.CreateVolumeResponse()
        out.volume.capacity_bytes = reply.volume.capacity_bytes
        out.volume.id = reply.volume.volume_id
        out.volume.attributes.update(reply.volume.volume_context)
        for topo in reply.volume.accessible_topology:
            out.volume.accessible_topology.append(
                _recode(topo, csi0_pb2.Topology)
            )
        return out

    def DeleteVolume(self, request, context) -> csi0_pb2.DeleteVolumeResponse:
        self.v1.DeleteVolume(
            csi_pb2.DeleteVolumeRequest(volume_id=request.volume_id), context
        )
        return csi0_pb2.DeleteVolumeResponse()

    def ValidateVolumeCapabilities(
        self, request, context
    ) -> csi0_pb2.ValidateVolumeCapabilitiesResponse:
        req = csi_pb2.ValidateVolumeCapabilitiesRequest(
            volume_id=request.volume_id,
            volume_capabilities=_recode_all(
                request.volume_capabilities, csi_pb2.VolumeCapability
            ),
        )
        req.volume_context.update(request.volume_attributes)
        reply = self.v1.ValidateVolumeCapabilities(req, context)
        return csi0_pb2.ValidateVolumeCapabilitiesResponse(
            supported=not reply.message, message=reply.message
        )

    def GetCapacity(self, request, context) -> csi0_pb2.GetCapacityResponse:
        reply = self.v1.GetCapacity(csi_pb2.GetCapacityRequest(), context)
        return csi0_pb2.GetCapacityResponse(
            available_capacity=reply.available_capacity
        )

    def ControllerGetCapabilities(
        self, request, context
    ) -> csi0_pb2.ControllerGetCapabilitiesResponse:
        reply = self.v1.ControllerGetCapabilities(
            csi_pb2.ControllerGetCapabilitiesRequest(), context
        )
        out = csi0_pb2.ControllerGetCapabilitiesResponse()
        for cap in reply.capabilities:
            # RPC capability types share numbering across versions — but
            # only advertise what this personality actually implements
            # (no v0 ListVolumes shim exists).
            if cap.rpc.type == csi_pb2.ControllerServiceCapability.RPC.LIST_VOLUMES:
                continue
            out.capabilities.add().rpc.type = cap.rpc.type
        return out


class NodeServer0:
    def __init__(self, node) -> None:
        self.v1 = node

    def NodeStageVolume(self, request, context) -> csi0_pb2.NodeStageVolumeResponse:
        req = csi_pb2.NodeStageVolumeRequest(
            volume_id=request.volume_id,
            staging_target_path=request.staging_target_path,
        )
        if request.HasField("volume_capability"):
            req.volume_capability.CopyFrom(
                _recode(request.volume_capability, csi_pb2.VolumeCapability)
            )
        req.publish_context.update(request.publish_info)
        req.volume_context.update(request.volume_attributes)
        self.v1.NodeStageVolume(req, context)
        return csi0_pb2.NodeStageVolumeResponse()

    def NodeUnstageVolume(
        self, request, context
    ) -> csi0_pb2.NodeUnstageVolumeResponse:
        self.v1.NodeUnstageVolume(
            csi_pb2.NodeUnstageVolumeRequest(
                volume_id=request.volume_id,
                staging_target_path=request.staging_target_path,
            ),
            context,
        )
        return csi0_pb2.NodeUnstageVolumeResponse()

    def NodePublishVolume(
        self, request, context
    ) -> csi0_pb2.NodePublishVolumeResponse:
        req = csi_pb2.NodePublishVolumeRequest(
            volume_id=request.volume_id,
            staging_target_path=request.staging_target_path,
            target_path=request.target_path,
            readonly=request.readonly,
        )
        if request.HasField("volume_capability"):
            req.volume_capability.CopyFrom(
                _recode(request.volume_capability, csi_pb2.VolumeCapability)
            )
        req.publish_context.update(request.publish_info)
        req.volume_context.update(request.volume_attributes)
        self.v1.NodePublishVolume(req, context)
        return csi0_pb2.NodePublishVolumeResponse()

    def NodeUnpublishVolume(
        self, request, context
    ) -> csi0_pb2.NodeUnpublishVolumeResponse:
        self.v1.NodeUnpublishVolume(
            csi_pb2.NodeUnpublishVolumeRequest(
                volume_id=request.volume_id, target_path=request.target_path
            ),
            context,
        )
        return csi0_pb2.NodeUnpublishVolumeResponse()

    def NodeGetId(self, request, context) -> csi0_pb2.NodeGetIdResponse:
        # v0-only RPC (removed in v1 in favor of NodeGetInfo).
        return csi0_pb2.NodeGetIdResponse(node_id=self.v1.node_id)

    def NodeGetCapabilities(
        self, request, context
    ) -> csi0_pb2.NodeGetCapabilitiesResponse:
        reply = self.v1.NodeGetCapabilities(
            csi_pb2.NodeGetCapabilitiesRequest(), context
        )
        out = csi0_pb2.NodeGetCapabilitiesResponse()
        for cap in reply.capabilities:
            out.capabilities.add().rpc.type = cap.rpc.type
        return out

    def NodeGetInfo(self, request, context) -> csi0_pb2.NodeGetInfoResponse:
        reply = self.v1.NodeGetInfo(csi_pb2.NodeGetInfoRequest(), context)
        out = csi0_pb2.NodeGetInfoResponse(
            node_id=reply.node_id,
            max_volumes_per_node=reply.max_volumes_per_node,
        )
        if reply.HasField("accessible_topology"):
            out.accessible_topology.CopyFrom(
                _recode(reply.accessible_topology, csi0_pb2.Topology)
            )
        return out
