"""oim-import-hf: HF Llama checkpoint → native params-only export.

Bridges public open-weight checkpoints into the framework: reads a
local ``transformers`` Llama-family directory, converts layout + RoPE
convention (oim_tpu/models/hf.py), and writes the same params-only
orbax export ``Checkpointer.export_params`` produces — directly
loadable by ``oim-serve --params-dir`` / ``oim-train --params-dir``.
Prints the geometry flags those binaries need to match the imported
model (their configs come from flags, not the export).

Thin flag→run wiring like every CLI here (≙ reference cmd/* shape,
/root/reference/cmd/oim-csi-driver/main.go:25-71).
"""

from __future__ import annotations

import argparse
import os
import sys


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="oim-import-hf",
        description="Convert a local HF Llama checkpoint to a native "
        "params export.",
    )
    p.add_argument(
        "--hf-dir", required=True,
        help="local transformers checkpoint directory (config.json + "
        "weights); no network fetch is attempted",
    )
    p.add_argument(
        "--out-dir", required=True,
        help="target directory for the params-only orbax export "
        "(must not exist)",
    )
    p.add_argument(
        "--param-dtype", default="float32",
        choices=("float32", "bfloat16"),
        help="storage dtype for the converted params",
    )
    p.add_argument(
        "--n-stages", type=int, default=1,
        help="pipeline stages to stack the layers for (must divide the "
        "checkpoint's layer count)",
    )
    return p


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    out_dir = os.path.abspath(args.out_dir)
    if os.path.exists(out_dir):
        print(f"refusing to overwrite {out_dir}", file=sys.stderr)
        return 1
    if not os.path.isdir(args.hf_dir):
        print(f"not a checkpoint directory: {args.hf_dir}", file=sys.stderr)
        return 1

    import torch
    import transformers

    from oim_tpu.models.hf import from_hf_llama, llama_config

    hf_config = transformers.AutoConfig.from_pretrained(args.hf_dir)
    cfg = llama_config(
        hf_config, param_dtype=args.param_dtype, n_stages=args.n_stages
    )
    model = transformers.AutoModelForCausalLM.from_pretrained(
        args.hf_dir, torch_dtype=torch.float32
    )
    params = from_hf_llama(model.state_dict(), cfg)
    del model

    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(out_dir, params)

    flags = (
        f"--vocab-size {cfg.vocab_size} --d-model {cfg.d_model} "
        f"--n-layers {cfg.n_layers} --n-heads {cfg.n_heads} "
        f"--n-kv-heads {cfg.n_kv_heads} --d-ff {cfg.d_ff} "
        f"--rope-theta {cfg.rope_theta} --norm-eps {cfg.norm_eps}"
        + (
            " --rope-scaling " + " ".join(str(v) for v in cfg.rope_scaling)
            if cfg.rope_scaling else ""
        )
        + (
            f" --sliding-window {cfg.sliding_window}"
            if cfg.sliding_window else ""
        )
        + (" --attn-bias" if cfg.attn_bias else "")
        + (f" --mlp-act {cfg.mlp_act}" if cfg.mlp_act != "silu" else "")
        + (" --norm-offset" if cfg.norm_offset else "")
        + (" --embed-scale" if cfg.embed_scale else "")
        + (
            f" --n-experts {cfg.n_experts} --moe-top-k {cfg.moe_top_k}"
            if cfg.n_experts else ""
        )
    )
    # Carry the model's tokenizer over (a sibling dir — the orbax
    # checkpoint tree must stay exactly what StandardCheckpointer
    # wrote): oim-serve --tokenizer-dir enables the text API with it.
    tok_dir = ""
    from oim_tpu.models.hf import TOKENIZER_FILES

    tok_files = [
        f
        for f in TOKENIZER_FILES
        if os.path.exists(os.path.join(args.hf_dir, f))
    ]
    if tok_files:
        import shutil

        tok_dir = out_dir + "-tokenizer"
        os.makedirs(tok_dir, exist_ok=True)
        for f in tok_files:
            shutil.copy2(os.path.join(args.hf_dir, f), tok_dir)

    print(f"imported {args.hf_dir} -> {out_dir}")
    print(
        f"train flags: {flags} --pp {cfg.n_stages} --params-dir {out_dir}"
    )
    tok_flag = f" --tokenizer-dir {tok_dir}" if tok_dir else ""
    if cfg.n_stages == 1:
        print(f"serve flags: {flags} --params-dir {out_dir}{tok_flag}")
    else:
        print(
            "serve: restack with --n-stages 1 first (oim-serve runs "
            "the layers unstaged)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
