"""oim-export-hf: native params export → HF Llama checkpoint directory.

The inverse of ``oim-import-hf``: loads a params-only orbax export
(``oim-train --export-dir`` / ``Checkpointer.export_params``), converts
it to the HF Llama layout (oim_tpu/models/hf.py ``to_hf_llama``), and
``save_pretrained``s a directory any ``transformers`` consumer loads —
models trained or fine-tuned here can leave the framework.

Geometry flags mirror oim-serve's (shapes alone cannot recover
n_heads); the roundtrip import(export(params)) == params is pinned by
tests/test_hf_import.py.
"""

from __future__ import annotations

import argparse
import os
import sys


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="oim-export-hf",
        description="Convert a native params export to an HF Llama "
        "checkpoint directory.",
    )
    p.add_argument("--params-dir", required=True)
    p.add_argument(
        "--out-dir", required=True, help="target HF directory (must not exist)"
    )
    p.add_argument("--vocab-size", type=int, required=True)
    p.add_argument("--d-model", type=int, required=True)
    p.add_argument("--n-layers", type=int, required=True)
    p.add_argument("--n-heads", type=int, required=True)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--d-ff", type=int, default=0)
    p.add_argument("--n-experts", type=int, default=0)
    p.add_argument("--moe-top-k", type=int, default=1)
    p.add_argument("--rope-theta", type=float, default=10000.0)
    p.add_argument(
        "--attn-bias", action="store_true",
        help="q/k/v projection biases (Qwen2-family)",
    )
    p.add_argument(
        "--mlp-act", default="silu", choices=["silu", "gelu_tanh"],
        help="MLP gate activation (gelu_tanh = Gemma GeGLU)",
    )
    p.add_argument(
        "--norm-offset", action="store_true",
        help="RMSNorm scales by (1 + weight) (Gemma family)",
    )
    p.add_argument(
        "--embed-scale", action="store_true",
        help="scale embeddings by sqrt(d_model) (Gemma family)",
    )
    p.add_argument(
        "--rope-scaling", type=float, nargs=4, default=[],
        metavar=("FACTOR", "LOW", "HIGH", "ORIG_MAX"),
    )
    p.add_argument("--norm-eps", type=float, default=1e-6)
    p.add_argument(
        "--max-position-embeddings", type=int, default=0,
        help="context length to record in config.json (0 = derive from "
        "rope-scaling, else transformers' default)",
    )
    p.add_argument(
        "--n-stages", type=int, default=1,
        help="pipeline stages the params were exported with (oim-train "
        "--pp); must match or the orbax restore shape-mismatches",
    )
    p.add_argument(
        "--tokenizer-dir", default="",
        help="tokenizer files to copy into the HF directory (e.g. the "
        "<ckpt>-tokenizer dir oim-import-hf created), so the export "
        "loads as a complete transformers checkpoint; default: the "
        "params dir's sibling <params-dir>-tokenizer when it exists",
    )
    return p


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    out_dir = os.path.abspath(args.out_dir)
    if os.path.exists(out_dir):
        print(f"refusing to overwrite {out_dir}", file=sys.stderr)
        return 1
    if args.tokenizer_dir:
        # Validate the cheap flag BEFORE minutes of restore/convert/save
        # (failing after would also leave out_dir populated, blocking
        # the corrected rerun on the overwrite guard above).  An explicit
        # dir must actually CONTAIN tokenizer files — an empty match
        # would silently produce the tokenizer-less checkpoint the user
        # specifically asked to avoid.
        from oim_tpu.models.hf import TOKENIZER_FILES

        if not any(
            os.path.isfile(os.path.join(args.tokenizer_dir, name))
            for name in TOKENIZER_FILES
        ):
            print(
                f"no tokenizer files in {args.tokenizer_dir} "
                f"(looked for {', '.join(TOKENIZER_FILES[:3])}, ...)",
                file=sys.stderr,
            )
            return 1

    import jax
    import torch
    import transformers

    from oim_tpu.checkpoint import load_params
    from oim_tpu.models import TransformerConfig, init_params
    from oim_tpu.models.hf import hf_llama_config_kwargs, to_hf_llama

    cfg = TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_experts=args.n_experts,
        moe_top_k=args.moe_top_k,
        attn_bias=args.attn_bias,
        mlp_act=args.mlp_act,
        norm_offset=args.norm_offset,
        embed_scale=args.embed_scale,
        d_ff=args.d_ff,
        rope_theta=args.rope_theta,
        rope_scaling=tuple(args.rope_scaling),
        norm_eps=args.norm_eps,
        n_stages=args.n_stages,
    )
    template = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg)
    )
    params = load_params(args.params_dir, template)
    sd = to_hf_llama(params, cfg)

    kwargs = hf_llama_config_kwargs(
        cfg, args.max_position_embeddings or None
    )
    if cfg.n_experts:
        # Native MoE == Mixtral's block-sparse layout (renormalized
        # top-k gates, SwiGLU experts) — export as the family itself.
        config = transformers.MixtralConfig(**kwargs)
        model_cls = transformers.MixtralForCausalLM
    elif cfg.gemma_numerics:
        config = transformers.GemmaConfig(**kwargs)
        model_cls = transformers.GemmaForCausalLM
    elif cfg.attn_bias:
        # qkv-bias-on/o-bias-off is exactly Qwen2's hardwired shape; a
        # LlamaConfig(attention_bias=True) model would also build an
        # o_proj bias this framework never carries, so the export MUST
        # be a Qwen2ForCausalLM (the family the weights came from).
        kwargs.pop("attention_bias", None)
        kwargs.pop("mlp_bias", None)
        config = transformers.Qwen2Config(**kwargs)
        model_cls = transformers.Qwen2ForCausalLM
    else:
        config = transformers.LlamaConfig(**kwargs)
        model_cls = transformers.LlamaForCausalLM
    # Meta-device construction skips torch's random init and the
    # duplicate full-precision allocation (assign=True adopts our
    # tensors directly) — an 8B export would otherwise pay minutes of
    # normal_() and 2x peak RAM for weights we immediately overwrite.
    with torch.device("meta"):
        model = model_cls(config)
    missing, unexpected = model.load_state_dict(
        {k: torch.as_tensor(v) for k, v in sd.items()},
        strict=False, assign=True,
    )
    if getattr(config, "tie_word_embeddings", False):
        # assign=True replaced embed_tokens.weight with a fresh tensor,
        # severing the lm_head tie (which still points at the meta
        # param); re-tie so save_pretrained never sees a meta tensor.
        model.tie_weights()
    # rotary buffers etc. are derived, not loaded, and a tied lm_head is
    # deliberately absent from the exported dict; real weights missing
    # means the conversion broke — fail loudly, never write half a model.
    real_missing = [
        m for m in missing
        if "rotary" not in m
        and not (
            m == "lm_head.weight"
            and getattr(config, "tie_word_embeddings", False)
        )
    ]
    if real_missing or unexpected:
        print(
            f"state dict mismatch: missing={real_missing[:4]} "
            f"unexpected={list(unexpected)[:4]}",
            file=sys.stderr,
        )
        return 1
    model.save_pretrained(out_dir)
    # Tokenizer symmetry with oim-import-hf: a complete HF checkpoint
    # carries its tokenizer, so downstream `AutoTokenizer.from_pretrained`
    # works on the export directly.  Same filename whitelist as the
    # import side — a user pointing --tokenizer-dir at a full HF
    # checkpoint must not clobber the just-written model files.
    tok_dir = args.tokenizer_dir or (
        args.params_dir.rstrip("/") + "-tokenizer"
    )
    if os.path.isdir(tok_dir):
        import shutil

        from oim_tpu.models.hf import TOKENIZER_FILES

        copied = 0
        for name in TOKENIZER_FILES:
            src = os.path.join(tok_dir, name)
            if os.path.isfile(src):
                shutil.copy2(src, out_dir)
                copied += 1
        print(f"tokenizer: copied {copied} files from {tok_dir}")
    print(f"exported {args.params_dir} -> {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
