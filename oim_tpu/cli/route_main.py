"""oim-route: the serving router binary.

Load-balances the oim-serve HTTP API over N backends, discovered
statically (repeatable ``--backend``) and/or dynamically from the
registry's ``serve/<id>/address`` keys (written by oim-serve
``--serve-id`` self-registration).  See serve/router.py for the
balancing/health/retry semantics.

Usage (static, CPU smoke):
    oim-route --backend http://127.0.0.1:8000 \\
              --backend http://127.0.0.1:8001 --port 9000
Usage (registry-discovered, mTLS):
    oim-route --registry-address tcp://registry:8370 \\
              --ca ca.crt --cert user.admin.crt --key user.admin.key
"""

from __future__ import annotations

import argparse

from oim_tpu import log


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="oim-route", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9000, help="0 = ephemeral")
    p.add_argument(
        "--backend", action="append", default=[],
        help="static backend url (repeatable)",
    )
    p.add_argument(
        "--registry-address", default="",
        help="discover backends from serve/<id>/address registry keys",
    )
    p.add_argument("--ca", help="CA cert file (enables registry mTLS)")
    p.add_argument("--cert", help="cert (e.g. CN user.admin)")
    p.add_argument("--key", help="key")
    p.add_argument("--health-interval", type=float, default=2.0)
    p.add_argument("--discover-interval", type=float, default=5.0)
    p.add_argument(
        "--unhealthy-after", type=int, default=2,
        help="consecutive failures before a backend is taken out",
    )
    p.add_argument(
        "--request-timeout", type=float, default=600.0,
        help="per-request backend timeout (matches oim-serve's result "
        "timeout)",
    )
    p.add_argument(
        "--affinity-prefix-tokens", type=int, default=32,
        help="route /v1/generate requests sharing this many leading "
        "token ids to one backend (its prefix cache holds them); 0 "
        "disables affinity",
    )
    p.add_argument(
        "--affinity-slack", type=int, default=2,
        help="max extra in-flight requests the affine backend may carry "
        "over the least-loaded one before affinity yields to balance",
    )
    p.add_argument(
        "--disagg-prompt-tokens", type=int, default=0, metavar="N",
        help="disaggregated prefill/decode (doc/serving.md): streamed "
        "token-list /v1/generate requests with at least N prompt "
        "tokens run prefill on a --pool prefill backend, ship the KV "
        "blocks to a --pool decode backend, and continue the stream "
        "there; 0 (default) disables.  Takes effect only while both "
        "pools have a healthy member; every ship failure falls back "
        "to the splice-recompute continuation (token-identical "
        "greedy)",
    )
    p.add_argument(
        "--disagg-first-tokens", type=int, default=1, metavar="K",
        help="token budget of the disaggregated prefill leg (the "
        "max_new_tokens clamp): K tokens stream from the prefill "
        "backend while the ship is in flight; keep it at/below the "
        "backend decode chunk",
    )
    p.add_argument(
        "--disagg-ship-timeout", type=float, default=30.0, metavar="S",
        help="per-leg timeout for the KV ship (GET /v1/kv + PUT "
        "/v1/kv); a slow ship falls back to recompute rather than "
        "stalling the client stream",
    )
    p.add_argument(
        "--no-residency", action="store_true",
        help="disable residency-aware routing (doc/serving.md 'Fleet "
        "prefix residency'): prompt-prefix affinity falls back to "
        "pure rendezvous, blind to where prefixes are actually "
        "resident — the bench's A/B control",
    )
    p.add_argument(
        "--no-prefix-fetch", action="store_true",
        help="never ship a resident prefix sibling→target on a miss; "
        "residency-aware ROUTING stays on, misses just recompute "
        "their prefill locally",
    )
    p.add_argument(
        "--prefix-fetch-timeout", type=float, default=10.0, metavar="S",
        help="per-ship timeout for a prefix fetch (GET /v1/kv?prefix= "
        "+ PUT /v1/kv); a slow fetch falls back to recompute",
    )
    p.add_argument(
        "--prefix-fetch-min-tokens", type=int, default=0, metavar="N",
        help="only fetch prefixes covering at least N tokens (0 = "
        "any): below the ship-vs-recompute crossover "
        "(doc/serving.md), recomputing is cheaper than shipping",
    )
    p.add_argument(
        "--qos-policy", default="", metavar="FILE",
        help="tenant QoS policy JSON (doc/serving.md 'Multi-tenant "
        "QoS'): per-tenant tiers, weights, request-rate and "
        "generated-token quotas.  The router becomes the quota layer "
        "(429 + per-tenant Retry-After on exhaustion).  With "
        "--registry-address and no file, the policy is fetched from "
        "the registry's qos/tenants key instead; neither = quotas off",
    )
    p.add_argument(
        "--http-tls", action="store_true",
        help="mTLS on the data plane with the same --ca/--cert/--key: "
        "the router's own listener requires client certs AND the router "
        "authenticates itself to mTLS backends",
    )
    p.add_argument(
        "--trace-file", default="",
        help="append spans to this JSONL (also $OIM_TRACE_FILE): the "
        "router span joins client→route→serve→engine traces in "
        "`oimctl trace`",
    )
    p.add_argument("--log-level", default="info")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    log.init_from_string(args.log_level)

    from oim_tpu.common import events, tracing

    # Observability parity with every other daemon (PR 3): named span
    # collector, flight-recorder ring behind GET /debugz, crash dump.
    tracing.init("oim-route", args.trace_file or None)
    events.init("oim-route")
    events.install_crash_hook()
    # Process self-telemetry (ISSUE 18): RSS/CPU/threads/GC gauges on
    # the same registry the router's MetricsServer renders.
    from oim_tpu.common import metrics as _metrics_mod

    _metrics_mod.install_process_metrics()

    from oim_tpu.serve.router import Router

    tls = None
    if args.ca:
        from oim_tpu.common.tlsconfig import load_tls

        tls = load_tls(args.ca, args.cert, args.key)
    ssl_context = client_ctx = None
    if args.http_tls:
        if not (args.ca and args.cert and args.key):
            raise SystemExit("--http-tls requires --ca/--cert/--key")
        from oim_tpu.serve.httptls import (
            client_ssl_context,
            server_ssl_context,
        )

        ssl_context = server_ssl_context(args.ca, args.cert, args.key)
        client_ctx = client_ssl_context(args.ca, args.cert, args.key)
    qos = None
    if args.qos_policy:
        from oim_tpu.qos.policy import load_policy_file

        qos = load_policy_file(args.qos_policy)
    elif args.registry_address:
        # No file given but a registry is: pull the operator-published
        # qos/tenants document.  Tolerant end to end — an absent key or
        # an unreachable registry at boot means quotas off, never a
        # dead router.
        try:
            from oim_tpu.common.regdial import registry_channel
            from oim_tpu.qos.publish import fetch_policy

            with registry_channel(args.registry_address, tls) as channel:
                fetched = fetch_policy(channel)
            if fetched.tenants:
                qos = fetched
        except Exception:
            qos = None
    try:
        router = Router(
            backends=tuple(args.backend),
            registry_address=args.registry_address,
            tls=tls,
            host=args.host,
            port=args.port,
            health_interval=args.health_interval,
            discover_interval=args.discover_interval,
            unhealthy_after=args.unhealthy_after,
            request_timeout=args.request_timeout,
            ssl_context=ssl_context,
            client_ssl_context=client_ctx,
            affinity_prefix_tokens=args.affinity_prefix_tokens,
            affinity_slack=args.affinity_slack,
            disagg_prompt_tokens=args.disagg_prompt_tokens,
            disagg_first_tokens=args.disagg_first_tokens,
            disagg_ship_timeout=args.disagg_ship_timeout,
            residency_aware=not args.no_residency,
            prefix_fetch=not args.no_prefix_fetch,
            prefix_fetch_timeout=args.prefix_fetch_timeout,
            prefix_fetch_min_tokens=args.prefix_fetch_min_tokens,
            qos=qos,
        ).start()
    except ValueError as exc:
        raise SystemExit(str(exc))
    log.current().info(
        "oim-route listening",
        host=router.host,
        port=router.port,
        static_backends=len(args.backend),
        registry=args.registry_address or "(none)",
    )
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
