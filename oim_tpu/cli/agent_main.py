"""tpu-agent-py: the Python fake device-plane daemon as a standalone process.

Development/test convenience only — production uses the C++ daemon under
native/tpu-agent (same protocol; tests/test_agent_protocol.py holds both to
identical behavior)."""

from __future__ import annotations

import argparse
import signal
import threading

from oim_tpu import log
from oim_tpu.agent import ChipStore, FakeAgentServer


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--socket", required=True)
    parser.add_argument("--fake-chips", type=int, default=8)
    parser.add_argument("--mesh", default="", help="e.g. 2x2x2")
    parser.add_argument("--state-dir", default="/tmp/tpu-agent-py")
    parser.add_argument("--accel-type", default="v5p")
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)

    log.init_from_string(args.log_level)
    mesh = (
        tuple(int(d) for d in args.mesh.split("x"))
        if args.mesh
        else (args.fake_chips,)
    )
    product = 1
    for d in mesh:
        product *= d
    if product != args.fake_chips:
        parser.error(f"--mesh {args.mesh} does not multiply to {args.fake_chips}")
    store = ChipStore(
        mesh=mesh, accel_type=args.accel_type, device_dir=args.state_dir
    )
    server = FakeAgentServer(store, args.socket).start()
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
