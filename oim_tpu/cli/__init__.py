"""CLI binaries (≙ reference cmd/*): thin flag → options → run wiring."""
