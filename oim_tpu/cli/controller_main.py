"""oim-controller: per-TPU-host controller (≙ reference cmd/oim-controller)."""

from __future__ import annotations

import argparse

from oim_tpu import log
from oim_tpu.common import events, metrics, tracing
from oim_tpu.common.tlsconfig import load_tls
from oim_tpu.controller import Controller


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--id", required=True, help="controller id")
    parser.add_argument(
        "--endpoint", default="tcp://0.0.0.0:8998", help="listen endpoint"
    )
    parser.add_argument(
        "--advertised-endpoint",
        default="",
        help="address registered with the registry (default: --endpoint)",
    )
    parser.add_argument(
        "--agent-socket",
        default="/var/run/tpu-agent/agent.sock",
        help="tpu-agent JSON-RPC socket",
    )
    parser.add_argument("--registry", default="", help="registry address")
    parser.add_argument(
        "--registry-delay",
        type=float,
        default=60.0,
        help="seconds between re-registrations",
    )
    parser.add_argument(
        "--health-interval",
        type=float,
        default=5.0,
        help="seconds between chip-health reports to the registry "
        "(leased health/<id>/<chip> keys; 0 disables)",
    )
    parser.add_argument(
        "--coordinator-host",
        default="127.0.0.1",
        help="host part of the JAX coordinator address handed to workloads",
    )
    parser.add_argument("--ca", help="CA cert file (enables mTLS)")
    parser.add_argument("--cert", help="cert (CN controller.<id>)")
    parser.add_argument("--key", help="key")
    parser.add_argument("--log-level", default="info")
    parser.add_argument(
        "--trace-file",
        default="",
        help="append spans as JSONL here (also $OIM_TRACE_FILE)",
    )
    parser.add_argument(
        "--metrics-endpoint",
        default="",
        help="serve Prometheus /metrics on this host:port "
        "(\":9090\" binds all interfaces)",
    )
    args = parser.parse_args(argv)

    log.init_from_string(args.log_level)
    tracing.init("oim-controller", args.trace_file or None)
    events.init("oim-controller")
    events.install_crash_hook()
    metrics_server = None
    if args.metrics_endpoint:
        metrics_server = metrics.MetricsServer(args.metrics_endpoint).start()
        log.current().info("metrics endpoint", port=metrics_server.port)
    tls = load_tls(args.ca, args.cert, args.key) if args.ca else None
    controller = Controller(
        args.id,
        args.agent_socket,
        registry_address=args.registry,
        tls=tls,
        registry_delay=args.registry_delay,
        coordinator_host=args.coordinator_host,
        health_interval=args.health_interval,
    )
    server = controller.start_server(args.endpoint)
    controller.start(args.advertised_endpoint or str(server.addr()))
    log.current().info(
        "oim-controller running", id=args.id, endpoint=str(server.addr())
    )
    try:
        server.wait()
    except KeyboardInterrupt:
        controller.close()
        server.stop()
    finally:
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
