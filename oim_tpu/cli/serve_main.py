"""oim-serve: the inference-serving binary.

The serving counterpart of ``oim-train``: loads a checkpoint (or random
weights for smoke tests), stands up the continuous-batching engine over a
slot-based KV cache, and serves token-id generation over HTTP.  Like the
trainer, it can take its accelerator binding from a CSI-staged bootstrap
(the pod's ``tpu-bootstrap.json``) — the workload the control plane
provisions slices *for*.

The reference framework has no serving surface (it is a storage control
plane); this is new work per SURVEY.md §2.3's TPU-build column.

Usage (smoke, CPU):
    JAX_PLATFORMS=cpu python -m oim_tpu.cli.serve_main \\
        --vocab-size 256 --d-model 64 --n-layers 2 --n-heads 4 \\
        --max-len 128 --port 8000
Then:
    curl -s localhost:8000/v1/generate -d \\
        '{"tokens": [1,2,3], "max_new_tokens": 8}'
"""

from __future__ import annotations

import argparse
import os

from oim_tpu import log


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="oim-serve", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000, help="0 = ephemeral")
    # Model geometry (must match the checkpoint when one is given).
    p.add_argument("--vocab-size", type=int, default=32768)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--d-ff", type=int, default=0)
    p.add_argument("--n-experts", type=int, default=0)
    p.add_argument("--moe-top-k", type=int, default=1)
    p.add_argument("--rope-theta", type=float, default=10000.0)
    p.add_argument(
        "--sliding-window", type=int, default=0,
        help="sliding-window attention (Mistral-family); 0 = full causal",
    )
    p.add_argument(
        "--rope-scaling", type=float, nargs=4, default=[],
        metavar=("FACTOR", "LOW", "HIGH", "ORIG_MAX"),
        help="Llama-3.1 RoPE frequency remap (factor low_freq_factor "
        "high_freq_factor original_max_position); omit for plain RoPE",
    )
    p.add_argument(
        "--norm-eps", type=float, default=1e-6,
        help="RMSNorm epsilon (imported HF Llama checkpoints use 1e-5)",
    )
    p.add_argument(
        "--attn-bias", action="store_true",
        help="q/k/v projection biases (Qwen2-family imports)",
    )
    p.add_argument(
        "--mlp-act", default="silu", choices=["silu", "gelu_tanh"],
        help="MLP gate activation (gelu_tanh = Gemma GeGLU)",
    )
    p.add_argument(
        "--norm-offset", action="store_true",
        help="RMSNorm scales by (1 + weight) (Gemma family)",
    )
    p.add_argument(
        "--embed-scale", action="store_true",
        help="scale embeddings by sqrt(d_model) (Gemma family)",
    )
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument(
        "--checkpoint-dir", default="",
        help="orbax checkpoint dir from oim-train (empty = random init)",
    )
    p.add_argument(
        "--params-dir", default="",
        help="params-only export from oim-train --export-dir (loads a "
        "third of the checkpoint bytes: no optimizer state)",
    )
    p.add_argument(
        "--params-peer", default="", metavar="URL",
        help="restore weights from a serving sibling's streamed "
        "GET /v1/weights instead of storage (scale-out fast bring-up: "
        "bounded by network, not checkpoint cold-start); validated "
        "against this instance's --vocab-size/--d-model/... geometry",
    )
    p.add_argument(
        "--prefix-prewarm", type=int, default=4, metavar="K",
        help="with --params-peer and --prefix-cache: also pull the "
        "weight-donor's K hottest resident prefix entries "
        "(GET /v1/kv?prefix=) and install them before serving, so the "
        "replica joins the fleet with its cohort's system prompts "
        "already resident (doc/serving.md 'Fleet prefix residency'); "
        "strictly best-effort — any failure degrades to normal "
        "bring-up (0 = off)",
    )
    # Engine shape.
    p.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel ways: shard params + KV cache over the "
        "first tp devices (ep below composes for MoE experts)",
    )
    p.add_argument(
        "--ep", type=int, default=1,
        help="expert-parallel ways for MoE serving (tp*ep devices total)",
    )
    p.add_argument(
        "--spec-decode", type=int, default=0,
        help="speculative decoding draft length (0 = off): prompt-lookup "
        "drafts verified draft_len+1 positions per slot per step, exact "
        "for greedy and sampled output alike",
    )
    p.add_argument(
        "--spec-ngram", type=int, default=2,
        help="n-gram length the prompt-lookup drafter matches on",
    )
    p.add_argument(
        "--draft-params-dir", default="",
        help="params-only export of a small DRAFT model (oim-train "
        "--export-dir): model-drafted speculation instead of prompt "
        "lookup (requires --spec-decode and the --draft-* geometry)",
    )
    p.add_argument("--draft-n-layers", type=int, default=0)
    p.add_argument("--draft-d-model", type=int, default=0)
    p.add_argument("--draft-n-heads", type=int, default=0)
    p.add_argument("--draft-n-kv-heads", type=int, default=0)
    p.add_argument("--draft-d-ff", type=int, default=0)
    p.add_argument("--n-slots", type=int, default=8)
    p.add_argument(
        "--max-queue", type=int, default=64,
        help="admission queue bound (HTTP 429 beyond it; 0 = unbounded)",
    )
    p.add_argument(
        "--brownout-max-tokens", type=int, default=0, metavar="N",
        help="brownout: under sustained queue pressure (queue >= 75%% of "
        "--max-queue for over a second), clamp incoming requests' "
        "max_new_tokens to N instead of letting the backlog grow to the "
        "hard 429 — degraded answers beat errors (0 = off)",
    )
    p.add_argument(
        "--request-ring", type=int, default=256, metavar="N",
        help="recently-completed-request ring size: per-request phase "
        "breakdowns (queue/admit/prefill/decode/stream) served at "
        "GET /debugz/requests and merged fleet-wide by the router at "
        "/v1/requests (`oimctl requests`); drop-oldest beyond N",
    )
    p.add_argument(
        "--slow-capture-e2e", type=float, default=0.0, metavar="S",
        help="tail-latency auto-capture: any request whose end-to-end "
        "latency reaches S seconds dumps its full phase trace, an "
        "engine stats snapshot, and the ring neighborhood to the "
        "flight dir ($OIM_FLIGHT_DIR) as a `serve.slow_capture` "
        "artifact (0 = off)",
    )
    p.add_argument(
        "--slow-capture-tpot-mult", type=float, default=0.0, metavar="M",
        help="relative slow-capture trigger: capture when a request's "
        "time-per-output-token exceeds M times the engine's token-rate "
        "EWMA — catches regressions without an absolute threshold "
        "(0 = off)",
    )
    p.add_argument(
        "--slow-capture-interval", type=float, default=60.0, metavar="S",
        help="minimum seconds between slow-capture dumps (rate limit: "
        "one bad burst must not fill the flight dir)",
    )
    p.add_argument(
        "--watchdog-interval", type=float, default=1.0, metavar="S",
        help="stall-watchdog poll interval: a decode chunk blocking the "
        "driver past max(--stall-floor, --stall-multiplier x its EWMA "
        "wall) fails in-flight requests fast and flips /healthz "
        "(0 = watchdog off)",
    )
    p.add_argument(
        "--stall-multiplier", type=float, default=8.0,
        help="stall verdict: device wait exceeding this multiple of the "
        "chunk-wall EWMA",
    )
    p.add_argument(
        "--stall-floor", type=float, default=30.0, metavar="S",
        help="never call a stall before this many seconds of device "
        "wait (headroom for one-off recompiles)",
    )
    p.add_argument(
        "--drain-timeout", type=float, default=120.0,
        help="seconds to let in-flight requests finish on SIGTERM before "
        "exiting",
    )
    p.add_argument("--max-len", type=int, default=1024)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument(
        "--pipeline-depth", type=int, default=2, choices=(1, 2),
        help="decode pipeline depth: 2 (default) dispatches chunk N+1 "
        "before chunk N's readback so device compute overlaps host "
        "emission; 1 is the serial dispatch-then-readback loop (A/B "
        "control; see doc/operations.md 'Serving pipeline tuning')",
    )
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument(
        "--prefill-chunk", type=int, default=0, metavar="T",
        help="chunked prefill: admit long prompts in T-token KV-write "
        "segments, capping peak admission activations at [slots, T, d] "
        "(0 = one-shot prefill)",
    )
    p.add_argument(
        "--prefix-cache", type=int, default=0, metavar="N",
        help="cache up to N prompt-KV entries (requests marked "
        "cache_prefix); later prompts sharing a cached prefix skip "
        "re-prefilling it",
    )
    quant = p.add_mutually_exclusive_group()
    quant.add_argument(
        "--weights-int8", action="store_true",
        help="weight-only int8 for the matmul weights (per-output-channel "
        "scales) — halves weight bytes, the small-batch decode bottleneck",
    )
    quant.add_argument(
        "--weights-int4", action="store_true",
        help="weight-only int4 with group-wise scales (--int4-group) — "
        "~0.56 bytes/weight; the next decode lever once GQA + int8 KV "
        "shrink the cache",
    )
    p.add_argument(
        "--int4-group", type=int, default=64,
        help="int4 scale group along the reduction axis (gcd-clamped to "
        "each layer's geometry)",
    )
    p.add_argument(
        "--no-penalties", action="store_true",
        help="disable sampling-penalty support (repetition/presence/"
        "frequency): skips the per-slot [n_slots, vocab] occurrence "
        "state - worth it at big vocab x many slots when no client "
        "penalizes",
    )
    kvq = p.add_mutually_exclusive_group()
    kvq.add_argument(
        "--kv-int8", action="store_true",
        help="int8-quantized KV cache (half the cache bandwidth decode "
        "pays; per-token/head scales)",
    )
    kvq.add_argument(
        "--kv-int4", action="store_true",
        help="int4-quantized KV cache (kv4: half int8's cache bytes "
        "again, per-block scales fused into the paged flash-decode "
        "kernel's operand read) — requires --kv-block; dense layouts "
        "reject it because only the paged pool carries the block "
        "scales (doc/serving.md 'Paged KV cache')",
    )
    p.add_argument(
        "--paged-kernel", choices=("auto", "on", "off"), default="auto",
        help="block-table-aware Pallas flash-decode kernel for paged "
        "engines (reads K/V straight from the block pool — no dense "
        "gather per layer per chunk): auto (default) = on when the "
        "backend is a TPU, on = force (interpret mode off-TPU, the "
        "exactness-matrix configuration), off = the gather path (the "
        "A/B control; flip here if the paged-vs-dense mismatch counter "
        "fires, doc/operations.md)",
    )
    p.add_argument(
        "--prefill-kernel", choices=("auto", "on", "off"),
        default="auto",
        help="block-table-aware Pallas flash-prefill kernel for paged "
        "engines (computes a prompt segment's causal attention reading "
        "prior K/V from the block pool and writes the segment's new "
        "K/V straight into the slot's blocks with fused quant — no "
        "dense KV intermediate): auto (default) = on when the backend "
        "is a TPU, on = force (interpret mode off-TPU, the exactness-"
        "matrix configuration), off = the gather/scatter path (the "
        "A/B control and exactness oracle; flip here if the prefill "
        "mismatch counter fires, doc/operations.md).  Pairs with "
        "--prefill-chunk: segments of long prompts then interleave "
        "with decode chunks (doc/serving.md 'Chunked flash-prefill')",
    )
    p.add_argument(
        "--kv-block", type=int, default=0, metavar="T",
        help="paged KV cache with T-token blocks (0 = dense per-slot "
        "regions): HBM is reserved per request's worst case instead of "
        "n_slots x max_len, prefix-cache hits alias blocks copy-free "
        "across concurrent requests, and admission backpressures on "
        "block exhaustion — raise --n-slots above the dense-equivalent "
        "count to cash the capacity in (doc/serving.md 'Paged KV "
        "cache'); T must divide --max-len",
    )
    p.add_argument(
        "--kv-blocks", type=int, default=0, metavar="N",
        help="paged pool size in blocks (0 = the dense cache's "
        "footprint, n_slots x max_len / --kv-block)",
    )
    p.add_argument(
        "--kv-host-bytes", type=int, default=0, metavar="B",
        help="host-RAM KV overflow tier budget in bytes (0 = off; "
        "requires --kv-block): prefix shortfalls DEMOTE idle entries "
        "to host RAM instead of destroying them (a later hit promotes "
        "the blocks back — no recompute prefill), and admissions that "
        "cannot fit can park the coldest slot's table there and "
        "restore it exactly when blocks free (doc/serving.md "
        "'Host-RAM KV overflow tier')",
    )
    p.add_argument(
        "--no-kv-park", action="store_true",
        help="with --kv-host-bytes: disable swap-based slot parking "
        "(demote/promote of idle prefix entries stays on) — parking "
        "trades a mid-stream victim's latency for the head-of-line "
        "admission, which latency-floor deployments may not want",
    )
    p.add_argument(
        "--pool", default="mixed", choices=("prefill", "decode", "mixed"),
        help="disaggregation pool role (doc/serving.md 'Disaggregated "
        "prefill/decode'): prefill = take long-prompt admissions and "
        "serve GET /v1/kv exports (pair with --kv-block; a dense "
        "prefill backend makes every ship fall back to recompute), "
        "decode = ingest shipped KV (PUT /v1/kv) and stream "
        "continuations, mixed (default) = serve everything, no ships; "
        "surfaced via /v1/info, load/serve.<id>, and the leased "
        "serve/<id>/pool registry key so oim-route partitions the "
        "fleet",
    )
    p.add_argument(
        "--bootstrap", default="",
        help="tpu-bootstrap.json path (default: $TPU_BOOTSTRAP when set)",
    )
    p.add_argument(
        "--trace-file", default="",
        help="append spans as JSON lines (default: $OIM_TRACE_FILE)",
    )
    p.add_argument(
        "--warmup-embed", action="store_true",
        help="also pre-compile the /v1/embed path at every bucket "
        "(one forward compile per bucket; skip unless serving embeds)",
    )
    p.add_argument(
        "--no-warmup", action="store_true",
        help="skip pre-compiling admit buckets + decode (first live "
        "requests then pay the 20-40s TPU compiles)",
    )
    # Self-registration: announce serve/<id>/address to the registry so
    # oim-route discovers this instance (serve/registration.py).
    p.add_argument(
        "--serve-id", default="",
        help="register as serve/<id>/address in the registry (requires "
        "--registry-address; cert CN serve.<id> under mTLS)",
    )
    p.add_argument("--registry-address", default="")
    p.add_argument(
        "--advertise", default="",
        help="address to register (default http://<host>:<port>)",
    )
    p.add_argument(
        "--registry-delay", type=float, default=60.0,
        help="seconds between re-registrations",
    )
    p.add_argument("--ca", help="CA cert file (enables registry mTLS)")
    p.add_argument("--cert", help="cert (CN serve.<id>)")
    p.add_argument("--key", help="key")
    p.add_argument(
        "--tokenizer-dir", default="",
        help="HF tokenizer directory (oim-import-hf copies it next to "
        "the weights): enables {'text': ...} requests and decoded-text "
        "replies on the HTTP API; without it this instance speaks "
        "token ids only",
    )
    p.add_argument(
        "--http-tls", action="store_true",
        help="serve the HTTP API over mTLS with the same --ca/--cert/"
        "--key: clients (oim-route, oimctl) must hold a deployment-CA "
        "cert or the handshake fails (the gRPC plane's mTLS-everywhere "
        "stance, on the data plane)",
    )
    p.add_argument(
        "--qos-policy", default="", metavar="FILE",
        help="tenant QoS policy JSON (doc/serving.md 'Multi-tenant "
        "QoS'): the engine admits by weighted fair share instead of "
        "FIFO and may preempt (park, never kill) a lower-tier tenant's "
        "slot for a higher-priority admission; empty = QoS off (pure "
        "FIFO, the pre-QoS behavior)",
    )
    p.add_argument("--log-level", default="info")
    return p


def make_engine(args):
    """Build the engine from parsed args (separated for tests)."""
    import jax

    from oim_tpu.models import TransformerConfig, init_params

    qos = None
    if getattr(args, "qos_policy", ""):
        from oim_tpu.qos.policy import load_policy_file

        # Tolerant load (defaults on a missing/torn file): a bad policy
        # document must degrade to FIFO, never block serving bring-up.
        qos = load_policy_file(args.qos_policy)
    from oim_tpu.serve import Engine

    cfg = TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        attn_bias=args.attn_bias,
        mlp_act=args.mlp_act,
        norm_offset=args.norm_offset,
        embed_scale=args.embed_scale,
        d_ff=args.d_ff or 4 * args.d_model,
        n_experts=args.n_experts,
        moe_top_k=args.moe_top_k,
        rope_theta=args.rope_theta,
        rope_scaling=tuple(args.rope_scaling),
        sliding_window=args.sliding_window,
        norm_eps=args.norm_eps,
        dtype=args.dtype,
    )
    if sum(bool(s) for s in (
        args.checkpoint_dir, args.params_dir, args.params_peer
    )) > 1:
        raise SystemExit(
            "--checkpoint-dir, --params-dir and --params-peer are exclusive"
        )
    serve_mesh = None
    if args.tp > 1 or args.ep > 1:
        from oim_tpu.parallel import build_mesh

        serve_mesh = build_mesh(
            tp=args.tp, ep=args.ep,
            devices=jax.devices()[: args.tp * args.ep],
        )
    peer_restored = False
    if args.params_peer:
        from oim_tpu.checkpoint import load_params_from_peer
        from oim_tpu.parallel import build_mesh
        from oim_tpu.serve.httptls import client_ssl_context

        def peer_template():
            # The sibling streams whatever IT serves — a quantized
            # sibling hands over int8 payloads + scale leaves directly
            # (no requantization on this side), so the validation
            # template must carry the same transform.
            params = init_params(jax.random.PRNGKey(0), cfg)
            if args.weights_int8:
                from oim_tpu.ops.quant import quantize_params_int8

                return quantize_params_int8(params)
            if args.weights_int4:
                from oim_tpu.ops.quant import quantize_params_int4

                return quantize_params_int4(params, group=args.int4_group)
            return params

        template = jax.eval_shape(peer_template)
        peer_ctx = None
        if args.params_peer.startswith("https://"):
            if not (args.ca and args.cert and args.key):
                raise SystemExit(
                    "an https --params-peer needs --ca/--cert/--key"
                )
            peer_ctx = client_ssl_context(args.ca, args.cert, args.key)
        quantized = args.weights_int8 or args.weights_int4
        params = load_params_from_peer(
            args.params_peer,
            template,
            # Quantized trees carry scale leaves the training-sharding
            # map does not know; the Engine re-places them with its own
            # serve shardings on construction.
            None if quantized else cfg,
            None if quantized else (
                serve_mesh or build_mesh(devices=jax.devices()[:1])
            ),
            ssl_context=peer_ctx,
        )
        peer_restored = True
    elif args.params_dir or args.checkpoint_dir:
        from oim_tpu.parallel import build_mesh

        # Shape/dtype template only — restoring immediately replaces it,
        # so never materialize a full random init.
        template = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg)
        )
        # Restore SHARDED over the serving mesh when one is set: a model
        # too large for one chip must never be materialized replicated
        # on device 0 first (the whole point of --tp serving).
        mesh = serve_mesh or build_mesh(devices=jax.devices()[:1])
        if args.params_dir:
            from oim_tpu.checkpoint import load_params

            params = load_params(args.params_dir, template, cfg, mesh)
        else:
            from oim_tpu.checkpoint import Checkpointer, CheckpointerOptions

            # Read-only open (create=False): a typo'd path must not leave
            # a plausible-looking empty checkpoint dir behind, and remote
            # stores (gs://...) stay supported — orbax resolves the path.
            with Checkpointer(
                args.checkpoint_dir, cfg, mesh,
                options=CheckpointerOptions(create=False),
            ) as ckpt:
                # Partial restore of the params subtree only: the
                # optimizer state's tree shape depends on the trainer's
                # flags, which the server neither has nor needs.  A
                # missing checkpoint fails loudly (FileNotFoundError) —
                # a serving daemon must never silently serve random
                # weights.
                params = ckpt.restore_params(lambda: template)
    else:
        params = init_params(jax.random.PRNGKey(0), cfg)
    if args.weights_int8 and not peer_restored:
        from oim_tpu.ops.quant import quantize_params_int8

        params = quantize_params_int8(params)
    elif args.weights_int4 and not peer_restored:
        from oim_tpu.ops.quant import quantize_params_int4

        params = quantize_params_int4(params, group=args.int4_group)
    draft_params = draft_cfg = None
    if args.draft_params_dir:
        from oim_tpu.checkpoint import load_params
        from oim_tpu.parallel import build_mesh

        if not (args.draft_n_layers and args.draft_d_model
                and args.draft_n_heads):
            raise SystemExit(
                "--draft-params-dir needs --draft-n-layers, "
                "--draft-d-model and --draft-n-heads"
            )
        draft_cfg = TransformerConfig(
            vocab_size=args.vocab_size,
            d_model=args.draft_d_model,
            n_layers=args.draft_n_layers,
            n_heads=args.draft_n_heads,
            n_kv_heads=args.draft_n_kv_heads,
            d_ff=args.draft_d_ff,
            rope_theta=args.rope_theta,
            norm_eps=args.norm_eps,
            dtype=args.dtype,
        )
        draft_template = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), draft_cfg)
        )
        draft_params = load_params(
            args.draft_params_dir, draft_template, draft_cfg,
            serve_mesh or build_mesh(devices=jax.devices()[:1]),
        )
    return Engine(
        params,
        cfg,
        n_slots=args.n_slots,
        max_len=args.max_len,
        chunk=args.chunk,
        top_k=args.top_k,
        top_p=args.top_p,
        kv_int8=args.kv_int8,
        kv_int4=args.kv_int4,
        prefix_cache_size=args.prefix_cache,
        mesh=serve_mesh,
        spec_decode=args.spec_decode,
        spec_ngram=args.spec_ngram,
        draft_params=draft_params,
        draft_cfg=draft_cfg,
        penalties=not args.no_penalties,
        max_queue=args.max_queue,
        prefill_chunk=args.prefill_chunk,
        pipeline_depth=args.pipeline_depth,
        brownout_max_tokens=args.brownout_max_tokens,
        request_ring=args.request_ring,
        kv_block=args.kv_block,
        kv_blocks=args.kv_blocks,
        kv_host_bytes=args.kv_host_bytes,
        kv_park=not args.no_kv_park,
        # auto = TPU-paged engines only (the Engine resolves the
        # backend); on/off are the explicit A/B handles.
        paged_kernel={"auto": None, "on": True, "off": False}[
            args.paged_kernel
        ],
        prefill_kernel={"auto": None, "on": True, "off": False}[
            args.prefill_kernel
        ],
        qos=qos,
        slow_capture_e2e_s=args.slow_capture_e2e,
        slow_capture_tpot_mult=args.slow_capture_tpot_mult,
        slow_capture_interval_s=args.slow_capture_interval,
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    log.init_from_string(args.log_level)
    # Registration misconfiguration must surface BEFORE the engine pays
    # its multi-minute compiles: validate flags + id shape at parse time.
    registration = None
    if args.serve_id:
        if not args.registry_address:
            raise SystemExit("--serve-id requires --registry-address")
        if not args.advertise and args.host in ("0.0.0.0", "::", ""):
            raise SystemExit(
                f"--host {args.host} binds a wildcard address; pass "
                "--advertise with the routable URL to register"
            )
        from oim_tpu.common.tlsconfig import load_tls
        from oim_tpu.serve.registration import ServeRegistration

        registration = ServeRegistration(
            args.serve_id,
            args.registry_address,
            args.advertise,  # filled in once the port is known
            tls=load_tls(args.ca, args.cert, args.key) if args.ca else None,
            delay=args.registry_delay,
            pool=args.pool,
        )
    from oim_tpu.common import events, tracing

    tracing.init("oim-serve", args.trace_file or None)
    events.init("oim-serve")
    events.install_crash_hook()
    # Performance forensics (ISSUE 18): the recompile sentinel's
    # process-global jax.monitoring listener must be registered BEFORE
    # the engine's warmup compiles so the warmup suppression bracket
    # sees every backend_compile event, and the process self-telemetry
    # gauges (RSS/CPU/threads/GC) ride the same metrics registry the
    # MetricsServer below renders.
    from oim_tpu.common import metrics as _metrics_mod
    from oim_tpu.serve import sentinel as _sentinel

    _sentinel.install()
    _metrics_mod.install_process_metrics()

    bootstrap_path = args.bootstrap or os.environ.get("TPU_BOOTSTRAP", "")
    if bootstrap_path:
        from oim_tpu.parallel import apply_chip_binding, load_bootstrap

        applied = apply_chip_binding(load_bootstrap(bootstrap_path))
        log.current().info("chip binding", path=bootstrap_path, applied=applied)

    from oim_tpu.serve.server import ServeServer

    # Cheap config pieces FIRST: a bad cert path or tokenizer dir must
    # surface before the engine pays its multi-minute compiles.
    ssl_context = None
    if args.http_tls:
        if not (args.ca and args.cert and args.key):
            raise SystemExit("--http-tls requires --ca/--cert/--key")
        from oim_tpu.serve.httptls import server_ssl_context

        ssl_context = server_ssl_context(args.ca, args.cert, args.key)
    tokenizer = None
    if args.tokenizer_dir:
        from oim_tpu.serve.texttok import TextTokenizer

        tokenizer = TextTokenizer(args.tokenizer_dir)
        log.current().info("tokenizer loaded", path=args.tokenizer_dir)
    engine = make_engine(args)
    if not args.no_warmup:
        log.current().info("warming up", buckets=list(engine.prompt_buckets))
        engine.warmup(embed=args.warmup_embed)
    if args.params_peer and args.prefix_prewarm > 0 and args.prefix_cache:
        # The --params-peer bring-up path's prefix leg (ISSUE 14):
        # pre-warm the weight-donor's hottest resident prefixes so the
        # replica's first requests hit instead of re-prefilling what
        # the fleet already computed.  AFTER warmup (the ingest write
        # is precompiled, the cache is clear of dummies), BEFORE the
        # serve loop starts (this thread is still the device writer).
        # Best-effort by contract: pre-warm failure must never block
        # replica readiness — log and serve cold.
        from oim_tpu.serve.disagg import prewarm_from_peer
        from oim_tpu.serve.httptls import opener as _peer_opener

        peer_ctx = None
        if args.params_peer.startswith("https://"):
            from oim_tpu.serve.httptls import client_ssl_context

            peer_ctx = client_ssl_context(args.ca, args.cert, args.key)
        try:
            n = prewarm_from_peer(
                engine, args.params_peer.rstrip("/"),
                args.prefix_prewarm,
                opener=_peer_opener(peer_ctx).open,
            )
            log.current().info(
                "prefix pre-warm", peer=args.params_peer, installed=n
            )
        except Exception as exc:
            log.current().warning(
                "prefix pre-warm failed; serving cold",
                peer=args.params_peer, error=str(exc),
            )
    server = ServeServer(
        engine, host=args.host, port=args.port, ssl_context=ssl_context,
        tokenizer=tokenizer,
        watchdog_interval=args.watchdog_interval,
        stall_multiplier=args.stall_multiplier,
        stall_floor_s=args.stall_floor,
        pool=args.pool,
    ).start()
    log.current().info(
        "oim-serve listening", host=server.host, port=server.port,
        n_slots=args.n_slots, max_len=args.max_len, mtls=server.tls,
    )
    event_publisher = None
    if registration is not None:
        scheme = "https" if ssl_context is not None else "http"
        registration.advertised_address = (
            args.advertise or f"{scheme}://{server.host}:{server.port}"
        )
        # Health-gated heartbeat: a latched driver death or decode
        # stall actively WITHDRAWS the discovery key (one watch event)
        # instead of waiting out probe failures + lease expiry.
        registration.health = lambda: server.error is None
        # Load telemetry beside the address beat: the leased
        # load/serve.<id> key the autoscaler's utilization rides on
        # (freshness = --registry-delay; lower it on autoscaled fleets,
        # doc/operations.md "Autoscaling").  The server's snapshot, not
        # the engine's: it adds the pool role the per-pool watermarks
        # partition on.
        registration.load = server.load_snapshot
        registration.start()
        # Durable WARNING+ publication under the serving identity (TLS
        # CN serve.<id> — the registry's events/ authz subtree).
        event_publisher = events.RegistryEventPublisher(
            f"serve.{args.serve_id}",
            args.registry_address,
            tls=registration.tls,
        ).start()
    import signal
    import threading
    import time as _time

    stop_evt = threading.Event()

    # Ctrl-C takes the same graceful-drain path as a rolling restart's
    # SIGTERM; a second Ctrl-C reverts to the default handler, so an
    # impatient operator can still hard-stop mid-drain.
    def _request_stop(*_):
        signal.signal(signal.SIGINT, signal.default_int_handler)
        stop_evt.set()

    signal.signal(signal.SIGTERM, _request_stop)
    signal.signal(signal.SIGINT, _request_stop)
    try:
        stop_evt.wait()
        # Graceful drain: deregister + stop admitting, let in-flight
        # requests finish (bounded), then exit — a rolling restart never
        # truncates a client's generation.
        if registration is not None:
            registration.stop()
            registration = None
        # Migrate-out drain (ISSUE 17): beyond stop-admitting, suspend
        # in-flight slots into /v1/slot records so the router ships
        # them to siblings instead of waiting out (or truncating)
        # their decode — the in_flight() wait below then clears as
        # soon as the slots are suspended, not when they finish.
        server.begin_drain()
        log.current().info(
            "draining", in_flight=engine.in_flight(),
            timeout_s=args.drain_timeout,
        )
        deadline = _time.monotonic() + args.drain_timeout
        while engine.in_flight() and _time.monotonic() < deadline:
            _time.sleep(0.2)
        # Settle: the last slot frees BEFORE its handler thread finishes
        # writing the response; exiting on the instant of in_flight()==0
        # would kill that daemon thread mid-delivery.
        _time.sleep(min(2.0, args.drain_timeout))
        log.current().info("drained", remaining=engine.in_flight())
    except KeyboardInterrupt:
        pass
    finally:
        if event_publisher is not None:
            event_publisher.close()
        if registration is not None:
            registration.stop()
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
