"""oim-autoscale: the fleet autoscaler daemon.

Watches the serving plane's load and health through the registry
(``serve/``, ``load/``, ``evictions/``, controller leases) and actuates
replica-count decisions through the controller's idempotent
ProvisionSlice / MapVolume RPCs plus a replica launcher — the
control↔serve loop closed (oim_tpu/autoscale, doc/operations.md
"Autoscaling").

State access is the FleetMonitor's: the autoscaler rides a RegistryDB.
Run it beside the registry on the registry's own store, or point
``--db etcd://host:port`` at a registry's ``--etcd-listen`` stand-in
(the replica-peering plane) to run it as a separate process:

    oim-registry --db state.sqlite --etcd-listen tcp://127.0.0.1:8380 &
    oim-autoscale --db etcd://127.0.0.1:8380 \\
        --registry-address tcp://127.0.0.1:8999 \\
        --controller c0 --controller c1 \\
        --min-replicas 1 --max-replicas 4 --chips-per-replica 2 \\
        --launch-arg python --launch-arg -m \\
        --launch-arg oim_tpu.cli.serve_main \\
        --launch-arg --serve-id --launch-arg '{id}' \\
        --launch-arg --registry-address \\
        --launch-arg tcp://127.0.0.1:8999 ...

Launched replicas self-register exactly like operator-started ones;
scale-in drains them through oim-serve's SIGTERM path before unmapping
the slice.  Use ``--params-peer`` style launch args pointing at a
serving sibling for network-bounded bring-up.
"""

from __future__ import annotations

import argparse

from oim_tpu import log


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="oim-autoscale", description=__doc__)
    p.add_argument(
        "--db",
        default="",
        help="registry state: empty = in-memory (tests only — the "
        "autoscaler must see the registry's real keyspace), "
        "etcd://host:port = a registry's --etcd-listen stand-in or real "
        "etcd, else a sqlite path (ONLY when embedded beside the "
        "registry that owns it)",
    )
    p.add_argument(
        "--registry-address",
        required=True,
        help="registry gRPC endpoint (the controller proxy hop the "
        "actuator dials)",
    )
    p.add_argument(
        "--controller",
        action="append",
        default=[],
        required=True,
        help="candidate controller id for slice placement (repeatable; "
        "tried in order, ENOSPC moves to the next)",
    )
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--chips-per-replica", type=int, default=1)
    p.add_argument(
        "--slots-per-replica", type=int, default=8,
        help="engine slot capacity assumed for backends that have not "
        "published load yet (match oim-serve --n-slots)",
    )
    p.add_argument("--high-watermark", type=float, default=0.8)
    p.add_argument("--low-watermark", type=float, default=0.3)
    p.add_argument("--max-step", type=int, default=1)
    p.add_argument("--scale-out-cooldown", type=float, default=30.0)
    p.add_argument("--scale-in-cooldown", type=float, default=120.0)
    p.add_argument("--eval-period", type=float, default=10.0)
    p.add_argument("--enospc-backoff", type=float, default=60.0)
    p.add_argument(
        "--stale-load",
        type=float,
        default=0.0,
        help="ignore load keys older than this many seconds (0 = never; "
        "set to ~3x the serve fleet's --registry-delay)",
    )
    p.add_argument(
        "--replica-prefix", default="asr-",
        help="managed replica ids are <prefix><k>; also the slice/volume "
        "name",
    )
    p.add_argument(
        "--launch-arg",
        action="append",
        default=[],
        help="one argv element of the replica launch command "
        "(repeatable, '{id}' substitutes the replica id); empty = "
        "actuate slices only and let an external supervisor run the "
        "processes",
    )
    p.add_argument(
        "--state-dir", default="_work/autoscale",
        help="per-replica bootstrap files for launched subprocesses",
    )
    p.add_argument(
        "--drain-timeout", type=float, default=150.0,
        help="seconds to wait for a scale-in victim's SIGTERM drain "
        "before SIGKILL",
    )
    p.add_argument(
        "--fleet-monitor",
        action="store_true",
        help="run a FleetMonitor on the same DB and wire its "
        "eviction/controller-dead classification into replacement "
        "directly (skip when the registry already runs one in-process "
        "with the autoscaler)",
    )
    p.add_argument("--ca", help="CA cert file (enables mTLS to the proxy)")
    p.add_argument("--cert", help="client cert (CN user.admin)")
    p.add_argument("--key", help="key")
    p.add_argument(
        "--metrics-endpoint",
        default="",
        help="serve Prometheus /metrics (+ /debugz) on this host:port",
    )
    p.add_argument("--trace-file", default="")
    p.add_argument("--log-level", default="info")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    log.init_from_string(args.log_level)

    from oim_tpu.autoscale import (
        Autoscaler,
        AutoscalePolicy,
        ControllerActuator,
        InProcessLauncher,
        SubprocessLauncher,
    )
    from oim_tpu.cli.registry_main import make_db
    from oim_tpu.common import events, metrics, tracing

    tracing.init("oim-autoscale", args.trace_file or None)
    events.init("oim-autoscale")
    events.install_crash_hook()
    metrics_server = None
    if args.metrics_endpoint:
        metrics_server = metrics.MetricsServer(args.metrics_endpoint).start()
        log.current().info("metrics endpoint", port=metrics_server.port)

    try:
        policy = AutoscalePolicy(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            chips_per_replica=args.chips_per_replica,
            slots_per_replica=args.slots_per_replica,
            high_watermark=args.high_watermark,
            low_watermark=args.low_watermark,
            max_step=args.max_step,
            scale_out_cooldown_s=args.scale_out_cooldown,
            scale_in_cooldown_s=args.scale_in_cooldown,
            eval_period_s=args.eval_period,
            enospc_backoff_s=args.enospc_backoff,
            stale_load_s=args.stale_load,
        )
    except ValueError as exc:
        raise SystemExit(str(exc))

    tls_loader = None
    if args.ca:
        from oim_tpu.common.tlsconfig import load_tls

        def tls_loader():  # reloaded per call: rotation-safe
            return load_tls(args.ca, args.cert, args.key)

    db = make_db(args.db)
    actuator = ControllerActuator(
        args.registry_address, args.controller, tls_loader=tls_loader
    )
    if args.launch_arg:
        launcher = SubprocessLauncher(
            args.launch_arg,
            args.state_dir,
            drain_timeout_s=args.drain_timeout,
        )
    else:
        # Slice-only actuation: an external supervisor (k8s, systemd)
        # owns the processes; launch/stop become no-ops it observes
        # through the registry.
        launcher = InProcessLauncher(lambda rid, placement: object())
    monitor = None
    if args.fleet_monitor:
        from oim_tpu.health import FleetMonitor

        monitor = FleetMonitor(db).start()
        log.current().info("fleet monitor running (embedded)")

    autoscaler = Autoscaler(
        db,
        policy,
        actuator,
        launcher,
        replica_prefix=args.replica_prefix,
        monitor=monitor,
    ).start()
    log.current().info(
        "oim-autoscale running",
        controllers=",".join(args.controller),
        min=args.min_replicas,
        max=args.max_replicas,
        eval_period=args.eval_period,
    )

    import signal
    import threading

    stop_evt = threading.Event()

    def _request_stop(*_):
        signal.signal(signal.SIGINT, signal.default_int_handler)
        stop_evt.set()

    signal.signal(signal.SIGTERM, _request_stop)
    signal.signal(signal.SIGINT, _request_stop)
    try:
        stop_evt.wait()
    except KeyboardInterrupt:
        pass
    finally:
        autoscaler.close()
        if monitor is not None:
            monitor.close()
        launcher.close()
        actuator.close()
        close = getattr(db, "close", None)
        if close is not None:
            close()
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
