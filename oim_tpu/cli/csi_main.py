"""oim-csi-driver: the CSI node/controller plugin (≙ reference
cmd/oim-csi-driver).  Local vs remote mode is chosen by which of
--agent-socket / --registry is set, exactly one required (≙ reference
cmd/oim-csi-driver/main.go:25-26, oim-driver.go:216-226)."""

from __future__ import annotations

import argparse

from oim_tpu import log
from oim_tpu.common import events, metrics, tracing
from oim_tpu.common.tlsconfig import load_tls
from oim_tpu.csi import OIMDriver
from oim_tpu.csi.mounter import BindMounter, Mounter


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--endpoint", default="unix:///csi/csi.sock", help="CSI endpoint"
    )
    parser.add_argument("--node-id", default="node-0")
    parser.add_argument("--driver-name", default="tpu.oim.io")
    parser.add_argument("--agent-socket", default="", help="local mode")
    parser.add_argument("--registry", dest="registry", default="", help="remote mode")
    parser.add_argument("--controller-id", default="")
    parser.add_argument("--ca", help="CA cert (remote mode mTLS)")
    parser.add_argument("--cert", help="cert (CN host.<controller-id>)")
    parser.add_argument("--key", help="key")
    parser.add_argument(
        "--emulate", default="", help="serve as this foreign driver (e.g. gke-tpu)"
    )
    parser.add_argument(
        "--bind-mount",
        action="store_true",
        help="publish via mount --bind (requires privilege)",
    )
    parser.add_argument("--device-timeout", type=float, default=60.0)
    parser.add_argument(
        "--csi-version",
        default="both",
        choices=["1.0", "0.3", "both"],
        help="CSI spec generation(s) to serve (≙ reference driver0.go "
        "legacy personality; 'both' serves csi.v1.* and csi.v0.* from "
        "the one socket)",
    )
    parser.add_argument("--log-level", default="info")
    parser.add_argument(
        "--trace-file",
        default="",
        help="append spans as JSONL here (also $OIM_TRACE_FILE)",
    )
    parser.add_argument(
        "--metrics-endpoint",
        default="",
        help="serve Prometheus /metrics on this host:port "
        "(\":9090\" binds all interfaces)",
    )
    args = parser.parse_args(argv)

    log.init_from_string(args.log_level)
    tracing.init("oim-csi-driver", args.trace_file or None)
    events.init("oim-csi-driver")
    events.install_crash_hook()
    event_publisher = None
    metrics_server = None
    if args.metrics_endpoint:
        metrics_server = metrics.MetricsServer(args.metrics_endpoint).start()
        log.current().info("metrics endpoint", port=metrics_server.port)
    tls_loader = None
    if args.ca:
        # Reload key material on every dial so rotation needs no restart
        # (≙ reference remote.go:101-114).
        ca, cert, key = args.ca, args.cert, args.key
        tls_loader = lambda: load_tls(ca, cert, key)  # noqa: E731
    driver = OIMDriver(
        csi_endpoint=args.endpoint,
        node_id=args.node_id,
        driver_name=args.driver_name,
        agent_socket=args.agent_socket,
        registry_address=args.registry,
        controller_id=args.controller_id,
        tls_loader=tls_loader,
        emulate=args.emulate,
        mounter=BindMounter() if args.bind_mount else Mounter(),
        device_timeout=args.device_timeout,
        csi_versions=(
            ("1.0", "0.3") if args.csi_version == "both" else (args.csi_version,)
        ),
    )
    if args.registry and args.controller_id:
        # Durable WARNING+ publication under the node identity (TLS CN
        # host.<controller-id> — the registry's events/ authz subtree);
        # tls_loader passes through so rotation applies per publish dial.
        event_publisher = events.RegistryEventPublisher(
            f"host.{args.controller_id}", args.registry, tls=tls_loader
        ).start()
    server = driver.start_server()
    log.current().info("oim-csi-driver running", endpoint=str(server.addr()))
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()
    finally:
        if event_publisher is not None:
            event_publisher.close()
        driver.close()
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
