"""oim-registry: the cluster registry daemon (≙ reference cmd/oim-registry)."""

from __future__ import annotations

import argparse

from oim_tpu import log
from oim_tpu.common.tlsconfig import load_tls
from oim_tpu.registry import MemRegistryDB, Registry, SqliteRegistryDB


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--endpoint", default="tcp://0.0.0.0:8999", help="listen endpoint"
    )
    parser.add_argument("--ca", help="CA cert file (enables mTLS)")
    parser.add_argument("--cert", help="server cert (CN component.registry)")
    parser.add_argument("--key", help="server key")
    parser.add_argument(
        "--db",
        default="",
        help="sqlite file for durable state; empty = in-memory",
    )
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)

    log.init_from_string(args.log_level)
    tls = None
    if args.ca:
        # Accept any CA-trusted client; per-method CN checks happen inside
        # (≙ reference cmd/oim-registry/main.go:53).
        tls = load_tls(args.ca, args.cert, args.key)
    db = SqliteRegistryDB(args.db) if args.db else MemRegistryDB()
    registry = Registry(db=db, tls=tls)
    server = registry.start_server(args.endpoint)
    log.current().info("oim-registry running", endpoint=str(server.addr()))
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
