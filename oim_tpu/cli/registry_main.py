"""oim-registry: the cluster registry daemon (≙ reference cmd/oim-registry)."""

from __future__ import annotations

import argparse

from oim_tpu import log
from oim_tpu.common import metrics, tracing
from oim_tpu.common.tlsconfig import load_tls
from oim_tpu.registry import (
    EtcdKVServer,
    EtcdRegistryDB,
    MemRegistryDB,
    Registry,
    SqliteRegistryDB,
)


def make_db(spec: str):
    """``--db`` forms: "" = in-memory, ``etcd://host:port`` = etcd v3 KV
    backend (the seam the reference reserved, registry.go:31-41), anything
    else = sqlite file path."""
    if not spec:
        return MemRegistryDB()
    if spec.startswith("etcd://"):
        return EtcdRegistryDB("tcp://" + spec[len("etcd://"):])
    return SqliteRegistryDB(spec)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--endpoint", default="tcp://0.0.0.0:8999", help="listen endpoint"
    )
    parser.add_argument("--ca", help="CA cert file (enables mTLS)")
    parser.add_argument("--cert", help="server cert (CN component.registry)")
    parser.add_argument("--key", help="server key")
    parser.add_argument(
        "--db",
        default="",
        help="durable state: empty = in-memory, etcd://host:port = etcd "
        "v3 cluster, else sqlite file path",
    )
    parser.add_argument(
        "--etcd-listen",
        default="",
        help="also serve the etcd v3 KV subset on this endpoint (an "
        "in-process etcd stand-in other registry replicas can point "
        "their --db etcd:// at)",
    )
    parser.add_argument(
        "--fleet-monitor",
        action="store_true",
        help="run the fleet health monitor next to the registry: watch "
        "health/ telemetry and controller leases, evict allocations on "
        "chip failure / controller death / operator drain "
        "(oim_tpu.health.FleetMonitor)",
    )
    parser.add_argument(
        "--degraded-grace",
        type=float,
        default=30.0,
        help="seconds a chip must stay DEGRADED before its allocation "
        "is drained (with --fleet-monitor)",
    )
    parser.add_argument(
        "--remap-backoff",
        type=float,
        default=0.0,
        help="seconds an evicted volume must wait before `oimctl remap` "
        "(with --fleet-monitor)",
    )
    parser.add_argument("--log-level", default="info")
    parser.add_argument(
        "--trace-file",
        default="",
        help="append spans as JSONL here (also $OIM_TRACE_FILE); merge "
        "files from several daemons with `oimctl trace`",
    )
    parser.add_argument(
        "--metrics-endpoint",
        default="",
        help="serve Prometheus /metrics on this host:port "
        "(\":9090\" binds all interfaces)",
    )
    args = parser.parse_args(argv)

    log.init_from_string(args.log_level)
    tracing.init("oim-registry", args.trace_file or None)
    from oim_tpu.common import events

    events.init("oim-registry")
    events.install_crash_hook()
    metrics_server = None
    if args.metrics_endpoint:
        metrics_server = metrics.MetricsServer(args.metrics_endpoint).start()
        log.current().info("metrics endpoint", port=metrics_server.port)
    tls = None
    if args.ca:
        # Accept any CA-trusted client; per-method CN checks happen inside
        # (≙ reference cmd/oim-registry/main.go:53).
        tls = load_tls(args.ca, args.cert, args.key)
    db = make_db(args.db)
    etcd_server = None
    if args.etcd_listen:
        # The backing store serves the etcd wire; this registry then reads
        # through the same etcd client as any peer replica would, so all
        # replicas (local and remote) see one namespaced keyspace.
        etcd_server = EtcdKVServer(db).start_server(args.etcd_listen)
        log.current().info(
            "etcd KV stand-in running", endpoint=str(etcd_server.addr())
        )
        db = EtcdRegistryDB(str(etcd_server.addr()))
    registry = Registry(db=db, tls=tls)
    monitor = None
    if args.fleet_monitor:
        from oim_tpu.health import EvictionPolicy, FleetMonitor

        monitor = FleetMonitor(
            db,
            policy=EvictionPolicy(
                degraded_grace_s=args.degraded_grace,
                remap_backoff_s=args.remap_backoff,
            ),
        ).start()
        log.current().info(
            "fleet monitor running",
            degraded_grace=args.degraded_grace,
            remap_backoff=args.remap_backoff,
        )
    # Durable flight-recorder publication for the registry process
    # itself (fleet-monitor evictions, breaker transitions, crashes of
    # its own threads): stores straight into the local db — no RPC.
    event_publisher = events.RegistryEventPublisher(
        "component.registry", db=db
    ).start()
    server = registry.start_server(args.endpoint)
    log.current().info("oim-registry running", endpoint=str(server.addr()))
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()
        if etcd_server is not None:
            etcd_server.stop()
    finally:
        event_publisher.close()
        if monitor is not None:
            monitor.close()
        registry.close()
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
