"""oimctl: operator tool for the registry (≙ reference cmd/oimctl).

    oimctl get [PATH]             read registry values
    oimctl set PATH VALUE         write a value (empty VALUE deletes;
                                  --ttl N leases it)
    oimctl watch [PATH]           stream changes (snapshot, then live)
    oimctl map VOLUME --controller ID --chips N    ad-hoc MapVolume
    oimctl unmap VOLUME --controller ID
    oimctl health                 fleet chip health, cordons, evictions
    oimctl drain ID [--reason R]  cordon a controller (evicts its volumes)
    oimctl uncordon ID            lift a cordon
    oimctl remap VOLUME --controller ID --chips N  clear eviction + map
    oimctl trace FILE [FILE...]   merge daemons' span files, print trees
    oimctl events [--volume X] [--component C] [--follow]
                                  flight-recorder timeline (registry
                                  events/ keys, /debugz URLs, dump files)
    oimctl requests [--slow N] [--tenant CN] [--errors]
                                  per-request latency breakdowns (queue/
                                  prefill/decode/stream + trace ids)
                                  from a router's /v1/requests or a
                                  backend's /debugz/requests
    oimctl top [--router URL] [--watch S]
                                  fleet load summary: per-backend queue/
                                  slots/token-rate/shed counters from the
                                  router's /v1/stats, or straight off the
                                  registry load/ keys when no router runs
    oimctl tenants [--router URL] per-tenant QoS view: tier, fair-share
                                  weight, quota pressure and throttles,
                                  live queue/active/parked counts, and
                                  the preemption ledger
    oimctl profile [--serve URL | --router URL --backend ID]
                   [--seconds N] [--out DIR]
                                  capture an on-demand device profiler
                                  trace from a live backend (POST
                                  /debugz/profile, poll, download the
                                  .tar.gz artifact)
    oimctl kv [--router URL | --serve URL] [--watch S]
                                  fleet KV-tier view: per-backend
                                  device/host occupancy, demote/promote
                                  flow (blocks, bytes, bandwidth),
                                  park/restore counts, hottest resident
                                  digest
"""

from __future__ import annotations

import argparse
import json
import time

import grpc

from oim_tpu import log
from oim_tpu.common import endpoint as ep
from oim_tpu.common import tracing
from oim_tpu.common import resilience
from oim_tpu.common.tlsconfig import load_tls
from oim_tpu.health import states as health_states
from oim_tpu.spec import CONTROLLER, REGISTRY, oim_pb2


def _channel(args):
    target = ep.parse(args.registry).grpc_target()
    if args.ca:
        tls = load_tls(args.ca, args.cert, args.key, "component.registry")
        return grpc.secure_channel(
            target, tls.channel_credentials(), options=tls.channel_options()
        )
    return grpc.insecure_channel(target)


def _map_and_print(
    channel, volume: str, controller: str, chips: int, rpc=lambda f: f()
) -> None:
    """One MapVolume through the proxy + the human-readable assignment —
    shared by `map` and `remap` so their request shape and output can
    never drift.  ``rpc`` is the retry wrapper (safe: controller MapVolume
    is volume_id-keyed idempotent)."""
    request = oim_pb2.MapVolumeRequest(volume_id=volume)
    if chips > 0:
        request.slice.chip_count = chips
    else:
        request.provisioned.SetInParent()
    reply = rpc(lambda: CONTROLLER.stub(channel).MapVolume(
        request,
        metadata=(("controllerid", controller),),
        timeout=60,
    ))
    print(f"mesh={list(reply.mesh.dims)}")
    print(f"coordinator={reply.coordinator_address}")
    for chip in reply.chips:
        print(
            f"chip {chip.chip_id}: {chip.device_path} "
            f"coord={list(chip.coord.coords)}"
        )


def _serve_urlopen(args, base: str):
    """urlopen for the serving HTTP plane: https targets reuse the
    gRPC plane's --ca/--cert/--key (mTLS, the `generate` command's
    convention).  Returns None (after printing) on misconfiguration."""
    import urllib.request

    if base.startswith("https://"):
        if not args.ca:
            print("error: https targets require --ca (and usually "
                  "--cert/--key for mTLS servers)")
            return None
        from oim_tpu.serve.httptls import client_ssl_context, opener

        return opener(client_ssl_context(args.ca, args.cert, args.key)).open
    return urllib.request.urlopen


def _render_requests(entries: list[dict], dropped: int) -> None:
    """The latency-breakdown table: per-phase milliseconds + the trace
    id prefix (16 hex chars — enough for `oimctl trace --trace-id`)."""
    def ms(value) -> str:
        return f"{float(value or 0.0) * 1000:9.1f}"

    print(
        f"{'RID':>5} {'BACKEND':<22} {'TENANT':<12} {'TIER':<11} "
        f"{'OUTCOME':<14} "
        f"{'E2E_MS':>9} {'QUEUE':>9} {'ADMIT':>9} {'PREFILL':>9} "
        f"{'DECODE':>9} {'STREAM':>9} {'CHUNKS':>6} {'SEGS':>4} "
        f"{'TOK i/o':>9} {'PREFIX':<10} TRACE"
    )
    for e in entries:
        tok = f"{e.get('tokens_in', 0)}/{e.get('tokens_out', 0)}"
        print(
            f"{e.get('rid', -1):>5} "
            f"{str(e.get('backend', '-'))[:22]:<22} "
            f"{str(e.get('tenant', ''))[:12]:<12} "
            # QoS tier the request ran under (ISSUE 16; '-' from rings
            # predating the field).
            f"{str(e.get('tier') or '-')[:11]:<11} "
            f"{str(e.get('outcome', '?'))[:14]:<14} "
            f"{ms(e.get('e2e_s'))} {ms(e.get('queue_s'))} "
            f"{ms(e.get('admit_s'))} "
            f"{ms(e.get('prefill_s'))} {ms(e.get('decode_s'))} "
            f"{ms(e.get('stream_s'))} {e.get('chunks', 0):>6} "
            # Chunked-prefill segment count (ISSUE 20; 0 from rings
            # predating the field, 1 = one-shot admission): a
            # neighbor's slow-TPOT window lining up with a many-SEGS
            # admission is interleaved long-prompt prefill, not a
            # backend stall.
            f"{e.get('prefill_segments', 0):>4} "
            f"{tok:>9} "
            # Which path produced the leading KV rows (ISSUE 14):
            # local/fetched prefix hit vs recomputed prefill — a slow
            # request whose cohort-mates say "fetched" while it says
            # "recomputed" is a residency miss worth triaging.
            f"{str(e.get('prefix', 'recomputed'))[:10]:<10} "
            f"{str(e.get('trace', ''))[:16]}"
        )
    if dropped:
        print(f"({dropped} older entries evicted from the ring)")


class _TopUnavailable(Exception):
    """Transient fleet-view fetch failure: fatal for a one-shot `top`,
    printed-and-retried under --watch (the standing incident view must
    not die on one dropped connection)."""


def _run_top(watch_s: float, fetch, render=None) -> int:
    """Shared `oimctl top` scaffold for both modes: ``fetch`` returns
    (rows, autoscale_line) or raises ``_TopUnavailable``.  One frame
    without --watch; with it, a flushed frame every ``watch_s`` seconds
    until interrupted.  ``render`` swaps the frame body (`oimctl kv`
    reuses the whole watch-loop contract with its own table)."""
    while True:
        if watch_s > 0:
            print(f"-- {time.strftime('%H:%M:%S')} --", flush=True)
        try:
            rows, line = fetch()
        except KeyboardInterrupt:
            # Ctrl-C lands mid-fetch as often as mid-sleep (the fetch
            # is where an outage loop spends its time): exit clean.
            return 0
        except _TopUnavailable as exc:
            if watch_s <= 0:
                print(f"error: {exc}")
                return 1
            print(f"error: {exc} (retrying)", flush=True)
        else:
            (render or _print_top)(rows, line)
            print("", end="", flush=True)  # frame out before the sleep
        if watch_s <= 0:
            return 0
        try:
            time.sleep(watch_s)
        except KeyboardInterrupt:
            return 0


def _print_top(
    rows: list[tuple[str, bool, dict]], autoscale_line: str = ""
) -> None:
    """One fleet-summary frame: per-backend pressure + the fleet
    utilization the autoscaler's band policy acts on."""
    print(
        f"{'BACKEND':<28} {'HEALTHY':<8} {'POOL':<8} {'QUEUE':>6} "
        f"{'ACTIVE':>7} {'SLOTS':>6} {'TOK/S':>9} {'KV f/s/t+host':>26} "
        f"{'PATH':>10} {'PFX':>9} {'PROMO p/d':>10} {'SHIP e/i':>9} "
        f"{'SHED q/d/b':>12} BROWNOUT"
    )
    busy = capacity = 0.0
    for bid, healthy, load in rows:
        q = load.get("queue_depth", 0)
        a = load.get("active_slots", 0)
        s = load.get("total_slots", 0)
        busy += q + a
        capacity += s
        kv_total = load.get("kv_blocks_total", 0)
        # free/shared/total paged-KV blocks + fragmentation % — the
        # replica's cache headroom (admissions defer on exhaustion) and
        # how much of it is allocated-but-idle tail; dense engines
        # report no pool.  With the host overflow tier (ISSUE 15), the
        # host tier's used/total blocks + its own frag % ride along —
        # the replica's SECOND capacity tier, where warm prefixes and
        # parked slots wait out HBM pressure.
        if kv_total:
            kv = (
                f"{load.get('kv_blocks_free', 0)}/"
                f"{load.get('kv_blocks_shared', 0)}/{kv_total} "
                f"{load.get('kv_fragmentation', 0.0):.0%}"
            )
            host_total = load.get("kv_host_blocks_total", 0)
            if host_total:
                host_used = host_total - load.get(
                    "kv_host_blocks_free", 0
                )
                kv += (
                    f"+{host_used}/{host_total} "
                    f"{load.get('kv_host_fragmentation', 0.0):.0%}"
                )
        else:
            kv = "-"
        # Which decode path the replica runs (ISSUE 13): the paged
        # flash kernel ("kernel", "+kv4" on the int4 rung) vs the
        # gather control ("gather") — the fast-path visibility the
        # kernel-mismatch triage in doc/operations.md keys on.  Dense
        # engines have neither.
        if kv_total:
            path = "kernel" if load.get("paged_kernel") else "gather"
            if load.get("kv_int4"):
                path += "+kv4"
        else:
            path = "-"
        # KV-ship participation (disaggregated fleets): exports served
        # (prefill side) / ingests staged (decode side).
        ship = (
            f"{load.get('kv_exports', 0)}/{load.get('kv_imports', 0)}"
            if load.get("kv_exports") or load.get("kv_imports")
            else "-"
        )
        # Host-tier movement (ISSUE 15): promoted / demoted blocks —
        # promote ≈ demote at high KV frag is the thrash signature
        # (doc/operations.md 'Host-tier capacity incidents'); a parked
        # count marks replicas currently swapping live slots.
        promo = (
            f"{load.get('kv_promotions', 0)}/{load.get('kv_demotions', 0)}"
            if load.get("kv_promotions") or load.get("kv_demotions")
            else "-"
        )
        if load.get("parked_slots"):
            promo += f" P{load.get('parked_slots')}"
        # Fleet prefix residency (ISSUE 14): resident digests and this
        # backend's own hit rate — which replicas actually HOLD the
        # hot prompts, vs recomputing them every request.
        n_digests = len(load.get("prefix_digests") or ())
        p_hits = load.get("prefix_hits", 0)
        p_total = p_hits + load.get("prefix_misses", 0)
        pfx = (
            f"{n_digests} {p_hits / p_total:.0%}" if p_total
            else (f"{n_digests} -" if n_digests else "-")
        )
        shed = (
            f"{load.get('shed_queue_full', 0)}/"
            f"{load.get('shed_deadline', 0)}/"
            f"{load.get('shed_brownout', 0)}"
        )
        # Migrate-out drain (ISSUE 17): a draining backend is still
        # healthy (it serves /v1/kv + /v1/slot pulls) but takes no new
        # work — the HEALTHY cell says so instead of a misleading
        # plain "yes".
        if load.get("draining"):
            health_cell = "DRAIN"
        else:
            health_cell = "yes" if healthy else "NO"
        print(
            f"{bid[:28]:<28} {health_cell:<8} "
            f"{str(load.get('pool') or 'mixed')[:8]:<8} {q:>6} "
            f"{a:>7} {s:>6} {load.get('token_rate', 0.0):>9.1f} "
            f"{kv:>26} {path:>10} {pfx:>9} {promo:>10} {ship:>9} "
            f"{shed:>12} {'yes' if load.get('brownout') else '-'}"
        )
    util = busy / capacity if capacity else 0.0
    print(
        f"fleet: {len(rows)} backends, util {util:.2f} "
        f"(busy {busy:g} / capacity {capacity:g})"
    )
    if autoscale_line:
        print(autoscale_line)


def _mib(n: float) -> str:
    return f"{float(n or 0) / (1024 * 1024):.1f}M"


def _print_kv(
    rows: list[tuple[str, bool, dict]], fleet_line: str = ""
) -> None:
    """One KV-tier frame (`oimctl kv`): per-backend tier occupancy and
    demote/promote flow.  Every field via .get() with a zero default —
    an old-schema publisher in a mixed fleet renders as zeros/dashes,
    never a crash (the tolerant-decode contract)."""
    print(
        f"{'BACKEND':<28} {'HEALTHY':<8} {'DEV u/t':>13} "
        f"{'HOST u/t':>13} {'PARKED':>6} {'PARK/UN':>9} "
        f"{'DEMOTE blk/MiB/bw':>19} {'PROMOTE blk/MiB/bw':>19} "
        f"HOT DIGEST"
    )
    for bid, healthy, load in rows:
        dev_total = load.get("kv_blocks_total", 0) or 0
        dev = (
            f"{dev_total - (load.get('kv_blocks_free', 0) or 0)}"
            f"/{dev_total}"
            if dev_total else "-"
        )
        host_total = load.get("kv_host_blocks_total", 0) or 0
        host = (
            f"{host_total - (load.get('kv_host_blocks_free', 0) or 0)}"
            f"/{host_total}"
            if host_total else "-"
        )

        def flow(blocks_key: str, bytes_key: str, secs_key: str) -> str:
            blocks = load.get(blocks_key, 0) or 0
            n_bytes = load.get(bytes_key, 0) or 0
            seconds = load.get(secs_key, 0.0) or 0.0
            if not blocks:
                return "-"
            bw = (
                f"{n_bytes / seconds / (1024 * 1024):.0f}MiB/s"
                if seconds > 0 and n_bytes else "-"
            )
            return f"{blocks}/{_mib(n_bytes)}/{bw}"

        digests = load.get("prefix_digests") or ()
        hot = "-"
        if digests and isinstance(digests[0], dict):
            hot = (
                f"{str(digests[0].get('digest', ''))[:12]} "
                f"({digests[0].get('hits', 0)} hits)"
            )
        print(
            f"{bid[:28]:<28} {('yes' if healthy else 'NO'):<8} "
            f"{dev:>13} {host:>13} "
            f"{load.get('parked_slots', 0) or 0:>6} "
            f"{load.get('kv_parks', 0) or 0}/"
            f"{load.get('kv_unparks', 0) or 0:<4} "
            f"{flow('kv_demotions', 'kv_demote_bytes', 'kv_demote_seconds'):>19} "
            f"{flow('kv_promotions', 'kv_promote_bytes', 'kv_promote_seconds'):>19} "
            f"{hot}"
        )
    if fleet_line:
        print(fleet_line)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--registry", default="tcp://127.0.0.1:8999")
    parser.add_argument("--ca")
    parser.add_argument("--cert", help="client cert (CN user.admin)")
    parser.add_argument("--key")
    parser.add_argument("--log-level", default="warning")
    parser.add_argument(
        "--max-attempts", type=int, default=0,
        help="transient-failure retries per RPC (0 = the OIM_RETRY_* env "
        "defaults; 1 disables retries)",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    get = sub.add_parser("get")
    get.add_argument("path", nargs="?", default="")
    set_ = sub.add_parser("set")
    set_.add_argument("path")
    set_.add_argument("value")
    set_.add_argument(
        "--ttl", type=int, default=0,
        help="lease the key: auto-deletes this many seconds after the "
        "last set that carried a ttl (0 = persistent)",
    )
    watch = sub.add_parser(
        "watch",
        help="stream registry changes at or below a path prefix "
        "(snapshot first, then one line per mutation; '=' with no value "
        "means deleted/expired) until interrupted",
    )
    watch.add_argument("path", nargs="?", default="")
    watch.add_argument(
        "--no-initial", action="store_true",
        help="skip the snapshot; print only live changes",
    )
    map_ = sub.add_parser("map")
    map_.add_argument("volume")
    map_.add_argument("--controller", required=True)
    map_.add_argument("--chips", type=int, default=0, help="0 = provisioned")
    unmap = sub.add_parser("unmap")
    unmap.add_argument("volume")
    unmap.add_argument("--controller", required=True)
    sub.add_parser(
        "health",
        help="fleet health: chips by state (from health/ telemetry), "
        "cordoned controllers, evicted volumes",
    )
    drain = sub.add_parser(
        "drain",
        help="cordon a controller: the fleet monitor evicts its "
        "allocations so they can be remapped elsewhere",
    )
    drain.add_argument("controller_id")
    drain.add_argument("--reason", default="operator drain")
    uncordon = sub.add_parser("uncordon", help="lift a drain cordon")
    uncordon.add_argument("controller_id")
    remap = sub.add_parser(
        "remap",
        help="clear a volume's eviction mark and map it on a (healthy) "
        "controller",
    )
    remap.add_argument("volume")
    remap.add_argument("--controller", required=True)
    remap.add_argument("--chips", type=int, default=0, help="0 = provisioned")
    remap.add_argument(
        "--force", action="store_true",
        help="ignore the eviction policy's remap backoff window",
    )
    topo = sub.add_parser("topology", help="chip inventory of a controller")
    topo.add_argument("--controller", required=True)
    slices = sub.add_parser("slices", help="allocations on a controller")
    slices.add_argument("--controller", required=True)
    generate = sub.add_parser(
        "generate", help="send a generation request to an oim-serve daemon"
    )
    generate.add_argument(
        "tokens", type=int, nargs="*", help="prompt token ids"
    )
    generate.add_argument(
        "--text", default=None,
        help="prompt as text instead of token ids (the serve instance "
        "must run --tokenizer-dir); the reply prints decoded text too",
    )
    generate.add_argument("--serve", default="http://127.0.0.1:8000")
    generate.add_argument("--max-new-tokens", type=int, default=16)
    generate.add_argument("--temperature", type=float, default=0.0)
    generate.add_argument("--top-p", type=float, default=None)
    generate.add_argument("--min-p", type=float, default=0.0)
    generate.add_argument("--repetition-penalty", type=float, default=1.0)
    generate.add_argument("--presence-penalty", type=float, default=0.0)
    generate.add_argument("--frequency-penalty", type=float, default=0.0)
    generate.add_argument("--seed", type=int, default=0)
    generate.add_argument("--logprobs", action="store_true")
    generate.add_argument(
        "--stream", action="store_true",
        help="print tokens as they decode (NDJSON lines)",
    )
    generate.add_argument(
        "--beam", type=int, default=0, metavar="K",
        help="beam-K search via /v1/beam (latency mode; excludes "
             "--stream/--logprobs/--temperature)",
    )
    generate.add_argument(
        "--eos-id", type=int, default=None,
        help="EOS token id for --beam (trims the winning hypothesis)",
    )
    trace = sub.add_parser(
        "trace", help="render cross-process traces from --trace-file JSONLs"
    )
    trace.add_argument("files", nargs="+")
    trace.add_argument(
        "--trace-id", default="", help="only this trace (prefix match)"
    )
    evt = sub.add_parser(
        "events",
        help="render the flight-recorder event timeline: durable WARNING+ "
        "events from the registry (default), a daemon's live ring "
        "(--debugz URL), or crash-dump files (--file)",
    )
    evt.add_argument(
        "--volume", default="", help="only events about this volume/subject"
    )
    evt.add_argument(
        "--component", default="", help="only events from this component"
    )
    evt.add_argument("--kind", default="", help="event-kind prefix filter")
    evt.add_argument(
        "--follow", action="store_true",
        help="stream live events from the registry (snapshot, then one "
        "line per new event) until interrupted",
    )
    evt.add_argument(
        "--debugz", action="append", default=[], metavar="URL",
        help="read a daemon's live ring from its metrics endpoint "
        "(http://host:port[/debugz]); repeatable",
    )
    evt.add_argument(
        "--file", action="append", default=[], metavar="PATH",
        help="read a flight-recorder dump file; repeatable",
    )
    reqs = sub.add_parser(
        "requests",
        help="render the recently-completed-request ring: one row per "
        "request with its per-phase latency breakdown and trace id "
        "(join with `oimctl trace --trace-id`)",
    )
    reqs.add_argument(
        "--serve", default="http://127.0.0.1:9000",
        help="router url (fleet-merged /v1/requests) or a single "
        "backend url (its /debugz/requests)",
    )
    reqs.add_argument(
        "--slow", type=int, default=0, metavar="N",
        help="the N slowest requests by e2e latency (default: newest)",
    )
    reqs.add_argument(
        "--tenant", default="", help="only this tenant CN's requests"
    )
    reqs.add_argument(
        "--errors", action="store_true",
        help="only failed requests (outcome != ok)",
    )
    reqs.add_argument(
        "--limit", type=int, default=30,
        help="rows to show without --slow (newest last)",
    )
    tenants = sub.add_parser(
        "tenants",
        help="per-tenant QoS view through a router's /v1/stats: tier, "
        "fair-share weight, quota pressure (tokens charged, "
        "throttles), live queue/active/parked counts, and the "
        "preemption ledger (doc/serving.md 'Multi-tenant QoS')",
    )
    tenants.add_argument(
        "--router", default="http://127.0.0.1:9000",
        help="router url (fleet-merged tenant rows from /v1/stats)",
    )
    top = sub.add_parser(
        "top",
        help="one-shot (or --watch) fleet load summary: per-backend "
        "queue depth, busy/total slots, token rate, shed/brownout "
        "state; registry mode (no --router) also prints the "
        "autoscaler's desired-vs-live line when replica records exist",
    )
    top.add_argument(
        "--router", default="",
        help="read the fleet through this router's /v1/stats instead "
        "of the registry's load/ keys",
    )
    top.add_argument(
        "--watch", type=float, default=0.0, metavar="S",
        help="refresh every S seconds until interrupted (0 = one shot)",
    )
    profile = sub.add_parser(
        "profile",
        help="capture a bounded on-demand device profiler trace from a "
        "live backend and download it as a .tar.gz (doc/operations.md "
        "'Performance forensics')",
    )
    profile.add_argument(
        "--serve", default="",
        help="backend url (direct POST /debugz/profile)",
    )
    profile.add_argument(
        "--router", default="",
        help="router url: fans the capture out to --backend",
    )
    profile.add_argument(
        "--backend", default="",
        help="backend id (or url) to trace when going through --router",
    )
    profile.add_argument(
        "--seconds", type=float, default=2.0, metavar="N",
        help="capture window (clamped to 0.05..60 by the backend)",
    )
    profile.add_argument(
        "--out", default=".", metavar="DIR",
        help="directory to write the trace tarball into",
    )
    kv = sub.add_parser(
        "kv",
        help="one-shot (or --watch) fleet KV-tier view: per-backend "
        "device/host tier occupancy, demote/promote flow rates and "
        "bytes, park/restore counts, hottest resident digest "
        "(doc/operations.md 'KV-tier flow incidents')",
    )
    kv.add_argument(
        "--router", default="http://127.0.0.1:9000",
        help="router url (per-backend load snapshots from /v1/stats)",
    )
    kv.add_argument(
        "--serve", default="",
        help="single-backend mode: read one engine's /v1/info load "
        "instead of a router fleet view",
    )
    kv.add_argument(
        "--watch", type=float, default=0.0, metavar="S",
        help="refresh every S seconds until interrupted (0 = one shot)",
    )

    args = parser.parse_args(argv)
    log.init_from_string(args.log_level)
    if args.command == "generate":
        import json as json_mod
        import urllib.request

        # https --serve targets use the same --ca/--cert/--key as the
        # gRPC plane (the shared _serve_urlopen convention).
        urlopen = _serve_urlopen(args, args.serve)
        if urlopen is None:
            return 2

        def post_request(path: str, payload: dict):
            return urllib.request.Request(
                f"{args.serve.rstrip('/')}{path}",
                data=json_mod.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )

        if (args.text is None) == (not args.tokens):
            print("error: give either prompt token ids or --text")
            return 2
        prompt = (
            {"text": args.text} if args.text is not None
            else {"tokens": args.tokens}
        )
        if args.beam:
            if args.stream or args.logprobs or args.temperature:
                print("error: --beam excludes --stream/--logprobs/"
                      "--temperature (beam is greedy latency mode)")
                return 2
            try:
                with urlopen(
                    post_request("/v1/beam", {
                        **prompt,
                        "max_new_tokens": args.max_new_tokens,
                        "beam_size": args.beam,
                        "eos_id": args.eos_id,
                    }),
                    timeout=600,
                ) as resp:
                    reply = json_mod.load(resp)
                print("tokens:", " ".join(str(t) for t in reply["tokens"]))
                if reply.get("text") is not None:
                    print("text:", reply["text"])
                print(f"score: {reply['score']:.4f}")
            except urllib.error.URLError as exc:
                print(f"error: {exc}")
                return 1
            return 0
        request = post_request("/v1/generate", {
            **prompt,
            "max_new_tokens": args.max_new_tokens,
            "temperature": args.temperature,
            "top_p": args.top_p,
            "min_p": args.min_p,
            "repetition_penalty": args.repetition_penalty,
            "presence_penalty": args.presence_penalty,
            "frequency_penalty": args.frequency_penalty,
            "seed": args.seed,
            "logprobs": args.logprobs,
            "stream": args.stream,
        })
        try:
            with urlopen(request, timeout=600) as response:
                if args.stream:
                    failed = False
                    for line in response:
                        text = line.decode().rstrip()
                        print(text)
                        try:
                            failed = failed or "error" in json_mod.loads(text)
                        except ValueError:
                            pass
                    if failed:  # scripted callers need the exit code
                        return 1
                else:
                    reply = json_mod.load(response)
                    print("tokens:", " ".join(str(t) for t in reply["tokens"]))
                    if reply.get("text") is not None:
                        print("text:", reply["text"])
                    if args.logprobs:
                        print(
                            "logprobs:",
                            " ".join(f"{p:.3f}" for p in reply["logprobs"]),
                        )
        except urllib.error.URLError as exc:
            print(f"error: {exc}")
            return 1
        return 0
    if args.command == "trace":
        try:
            spans = tracing.load_jsonl(args.files)
        except OSError as exc:
            print(f"error: {exc}")
            return 1
        if args.trace_id:
            spans = [s for s in spans if s.trace_id.startswith(args.trace_id)]
        print(tracing.render_traces(spans))
        return 0
    if args.command == "events" and (args.file or args.debugz):
        # Offline/sideband sources need no registry connection.
        if args.follow:
            print("error: --follow streams from the registry and excludes "
                  "--file/--debugz")
            return 2
        from oim_tpu.common import events as events_mod

        evts = []
        try:
            for path in args.file:
                evts.extend(events_mod.load_dump(path))
            for url in args.debugz:
                import urllib.request

                full = url.rstrip("/")
                if not full.endswith("/debugz"):
                    full += "/debugz"
                with urllib.request.urlopen(full, timeout=10) as resp:
                    evts.extend(events_mod.events_from_doc(json.load(resp)))
        except (OSError, ValueError) as exc:
            print(f"error: {exc}")
            return 1
        print(events_mod.render_timeline(
            evts, volume=args.volume, component=args.component,
            kind=args.kind,
        ))
        return 0
    if args.command == "requests":
        import urllib.error

        base = args.serve.rstrip("/")
        urlopen = _serve_urlopen(args, base)
        if urlopen is None:
            return 2
        doc = None
        # A router serves the fleet-merged /v1/requests; a single
        # backend serves /debugz/requests — accept either target.
        for path in ("/v1/requests", "/debugz/requests"):
            try:
                with urlopen(base + path, timeout=30) as resp:
                    doc = json.load(resp)
                break
            except urllib.error.HTTPError as exc:
                if exc.code == 404:
                    continue
                print(f"error: {exc}")
                return 1
            except (urllib.error.URLError, OSError, ValueError) as exc:
                print(f"error: {exc}")
                return 1
        if doc is None:
            print(f"error: neither /v1/requests nor /debugz/requests "
                  f"answered at {base}")
            return 1
        entries = [
            e for e in doc.get("requests", []) if isinstance(e, dict)
        ]
        if args.tenant:
            entries = [
                e for e in entries if e.get("tenant") == args.tenant
            ]
        if args.errors:
            entries = [e for e in entries if e.get("outcome") != "ok"]
        if args.slow > 0:
            entries.sort(
                key=lambda e: -float(e.get("e2e_s", 0.0) or 0.0)
            )
            entries = entries[: args.slow]
        else:
            entries = entries[-args.limit:]
        for bid, err in sorted((doc.get("errors") or {}).items()):
            print(f"note: backend {bid} unreadable: {err}")
        _render_requests(entries, int(doc.get("dropped", 0) or 0))
        return 0
    if args.command == "tenants":
        import urllib.error

        base = args.router.rstrip("/")
        urlopen = _serve_urlopen(args, base)
        if urlopen is None:
            return 2
        try:
            with urlopen(base + "/v1/stats", timeout=30) as resp:
                stats = json.load(resp)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            print(f"error: {exc}")
            return 1
        qos = stats.get("qos") or {}
        rows = qos.get("tenants") or {}
        if not isinstance(rows, dict) or not rows:
            print("no tenant activity recorded"
                  + ("" if qos.get("enabled") else " (QoS off)"))
            return 0
        print(
            f"{'TENANT':<20} {'TIER':<11} {'WEIGHT':>6} {'QUEUED':>6} "
            f"{'ACTIVE':>6} {'PARKED':>6} {'ADMIT':>7} {'THROTTLE':>8} "
            f"{'PREEMPT':>7} {'VICTIM':>6} {'REQS':>7} {'TOK_OUT':>10} "
            f"{'QUOTA':>16}"
        )
        # Premium first, then by traffic: the starvation-diagnosis
        # read order (doc/operations.md) — is the top tier actually
        # getting served, and who is it displacing.
        tier_rank = {"premium": 0, "standard": 1, "best_effort": 2}
        for name in sorted(
            rows,
            key=lambda n: (
                tier_rank.get(rows[n].get("tier"), 1),
                -int(rows[n].get("requests", 0) or 0),
                n,
            ),
        ):
            r = rows[name]
            # Quota column: what the router charged vs the refill rate
            # ("-" = no quota configured for the tenant).
            rps = float(r.get("rate_rps", 0.0) or 0.0)
            tps = float(r.get("tokens_per_s", 0.0) or 0.0)
            if rps or tps:
                quota = (
                    f"{r.get('tokens_charged', 0)}@"
                    + (f"{tps:g}t/s" if tps else f"{rps:g}r/s")
                )
            else:
                quota = "-"
            print(
                f"{str(name)[:20]:<20} "
                f"{str(r.get('tier', '-'))[:11]:<11} "
                f"{float(r.get('weight', 0.0) or 0.0):>6.1f} "
                f"{r.get('queued', 0):>6} {r.get('active', 0):>6} "
                f"{r.get('parked', 0):>6} {r.get('admitted', 0):>7} "
                f"{r.get('throttled', 0):>8} {r.get('preempted', 0):>7} "
                f"{r.get('parked_victim', 0):>6} {r.get('requests', 0):>7} "
                f"{r.get('tokens_out', 0):>10} {quota:>16}"
            )
        print(
            f"qos: {'on' if qos.get('enabled') else 'off'}, "
            f"fleet preemptions {qos.get('fleet_preemptions', 0)}"
        )
        return 0
    if args.command == "top" and args.router:
        import urllib.error

        base = args.router.rstrip("/")
        urlopen = _serve_urlopen(args, base)
        if urlopen is None:
            return 2

        def fetch_router_top():
            try:
                with urlopen(base + "/v1/stats", timeout=30) as resp:
                    stats = json.load(resp)
            except (urllib.error.URLError, OSError, ValueError) as exc:
                raise _TopUnavailable(str(exc))
            # Fleet prefix-residency summary from the router's own
            # /v1/stats (the per-backend PFX column shows who HOLDS
            # what; this line is the fleet-level outcome).
            prefix = stats.get("prefix") or {}
            line = ""
            if prefix:
                total = (
                    prefix.get("fleet_hits", 0)
                    + prefix.get("fleet_misses", 0)
                )
                rate = (
                    f"{prefix.get('fleet_hits', 0) / total:.0%}"
                    if total else "-"
                )
                line = (
                    f"prefix: {prefix.get('residency_digests', 0)} "
                    f"resident digests, fleet hit rate {rate}, "
                    f"fetched {prefix.get('fetched', 0)}, "
                    f"fell_back {prefix.get('fell_back', 0)}"
                    + (
                        "" if prefix.get("residency_aware", True)
                        else " (residency-blind)"
                    )
                )
            return [
                (bid, bool(b.get("healthy", True)), b.get("load") or {})
                for bid, b in sorted(
                    (stats.get("backends") or {}).items()
                )
            ], line

        return _run_top(args.watch, fetch_router_top)
    if args.command == "profile":
        import json as json_mod
        import os
        import urllib.error
        import urllib.parse
        import urllib.request as urlreq

        if bool(args.serve) == bool(args.router):
            print("error: give exactly one of --serve URL (direct) or "
                  "--router URL --backend ID")
            return 2
        if args.router and not args.backend:
            print("error: --router mode needs --backend ID (the "
                  "profiler is per-backend state)")
            return 2
        base = (args.serve or args.router).rstrip("/")
        urlopen = _serve_urlopen(args, base)
        if urlopen is None:
            return 2
        qs = (
            f"backend={urllib.parse.quote(args.backend)}"
            if args.router else ""
        )
        start_url = base + "/debugz/profile" + (f"?{qs}" if qs else "")
        download_url = base + "/debugz/profile?" + (
            f"{qs}&" if qs else ""
        ) + "download=1"
        try:
            with urlopen(urlreq.Request(
                start_url,
                data=json_mod.dumps({"seconds": args.seconds}).encode(),
                headers={"Content-Type": "application/json"},
            ), timeout=30) as resp:
                started = json_mod.load(resp)
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")[:300]
            print(f"error: starting profile failed: {exc.code} {detail}")
            return 1
        except (urllib.error.URLError, OSError, ValueError) as exc:
            print(f"error: starting profile failed: {exc}")
            return 1
        doc = started.get("profile") or {}
        print(
            f"capturing {doc.get('seconds', args.seconds)}s trace "
            f"into {doc.get('dir', '?')} ..."
        )
        deadline = time.monotonic() + float(args.seconds) + 30.0
        state = str(doc.get("state", "running"))
        while state == "running" and time.monotonic() < deadline:
            time.sleep(0.25)
            try:
                with urlopen(start_url, timeout=10) as resp:
                    doc = json_mod.load(resp).get("profile") or {}
            except (urllib.error.URLError, OSError, ValueError):
                continue  # transient poll failure; the deadline bounds us
            state = str(doc.get("state", ""))
        if state != "done":
            err = str(doc.get("error") or "")
            print(
                f"error: profile did not finish: state={state or '?'}"
                + (f" ({err})" if err else "")
            )
            return 1
        try:
            with urlopen(download_url, timeout=120) as resp:
                data = resp.read()
                cdisp = resp.headers.get("Content-Disposition", "")
        except (urllib.error.URLError, OSError) as exc:
            print(f"error: downloading trace failed: {exc}")
            return 1
        name = ""
        if 'filename="' in cdisp:
            name = cdisp.split('filename="', 1)[1].split('"', 1)[0]
        name = (
            name
            or os.path.basename(str(doc.get("tar") or ""))
            or "oim-profile.tar.gz"
        )
        os.makedirs(args.out, exist_ok=True)
        path = os.path.join(args.out, name)
        with open(path, "wb") as f:
            f.write(data)
        print(f"wrote {path} ({len(data)} bytes)")
        return 0
    if args.command == "kv":
        import urllib.error

        base = (args.serve or args.router).rstrip("/")
        urlopen = _serve_urlopen(args, base)
        if urlopen is None:
            return 2

        if args.serve:
            # Single-backend mode: the engine's live load snapshot off
            # /v1/info — same fields the router's fleet view merges.
            def fetch_kv():
                try:
                    with urlopen(base + "/v1/info", timeout=30) as resp:
                        info = json.load(resp)
                except (urllib.error.URLError, OSError, ValueError) as exc:
                    raise _TopUnavailable(str(exc))
                return [(base, True, info.get("load") or {})], ""
        else:
            def fetch_kv():
                try:
                    with urlopen(base + "/v1/stats", timeout=30) as resp:
                        stats = json.load(resp)
                except (urllib.error.URLError, OSError, ValueError) as exc:
                    raise _TopUnavailable(str(exc))
                fleet = stats.get("kv") or {}
                line = ""
                if fleet:
                    line = (
                        "fleet: demoted "
                        f"{fleet.get('kv_demotions', 0)} blk "
                        f"({_mib(fleet.get('kv_demote_bytes', 0))}), "
                        f"promoted {fleet.get('kv_promotions', 0)} blk "
                        f"({_mib(fleet.get('kv_promote_bytes', 0))}), "
                        f"parks {fleet.get('kv_parks', 0)}/"
                        f"{fleet.get('kv_unparks', 0)}, parked "
                        f"{fleet.get('parked_slots', 0)}, device free "
                        f"{fleet.get('kv_blocks_free', 0)}/"
                        f"{fleet.get('kv_blocks_total', 0)} blk, "
                        f"host free "
                        f"{fleet.get('kv_host_blocks_free', 0)}/"
                        f"{fleet.get('kv_host_blocks_total', 0)} blk"
                    )
                return [
                    (
                        bid,
                        bool(b.get("healthy", True)),
                        b.get("load") or {},
                    )
                    for bid, b in sorted(
                        (stats.get("backends") or {}).items()
                    )
                ], line

        return _run_top(args.watch, fetch_kv, render=_print_kv)
    channel = _channel(args)
    # Operator CLI resilience: UNAVAILABLE/DEADLINE_EXCEEDED retried with
    # backoff under the shared policy.  Streaming `watch` is exempt — a
    # broken stream is surfaced, not silently resumed (resuming would
    # replay the snapshot and double-print events).
    policy = (
        resilience.RetryPolicy.from_env()
        if args.max_attempts <= 0
        else resilience.RetryPolicy.from_env(max_attempts=args.max_attempts)
    )

    def rpc(call):
        return resilience.call_with_retry(
            lambda _attempt: call(),
            policy,
            component="oimctl",
            op=args.command,
        )

    try:
        if args.command == "get":
            reply = rpc(lambda: REGISTRY.stub(channel).GetValues(
                oim_pb2.GetValuesRequest(path=args.path), timeout=30
            ))
            for value in reply.values:
                print(f"{value.path}={value.value}")
        elif args.command == "set":
            rpc(lambda: REGISTRY.stub(channel).SetValue(
                oim_pb2.SetValueRequest(
                    value=oim_pb2.Value(path=args.path, value=args.value),
                    ttl_seconds=args.ttl,
                ),
                timeout=30,
            ))
        elif args.command == "watch":
            call = REGISTRY.stub(channel).WatchValues(
                oim_pb2.WatchValuesRequest(
                    path=args.path, send_initial=not args.no_initial
                )
            )
            try:
                for reply in call:
                    if reply.initial_done:
                        print("-- initial snapshot complete --", flush=True)
                        continue
                    print(
                        f"{reply.value.path}={reply.value.value}", flush=True
                    )
            except KeyboardInterrupt:
                call.cancel()
            except grpc.RpcError as exc:
                if resilience.status_of(exc) != grpc.StatusCode.CANCELLED:
                    print(f"error: {resilience.error_text(exc)}")
                    return 1
        elif args.command == "map":
            _map_and_print(
                channel, args.volume, args.controller, args.chips, rpc=rpc
            )
        elif args.command == "unmap":
            rpc(lambda: CONTROLLER.stub(channel).UnmapVolume(
                oim_pb2.UnmapVolumeRequest(volume_id=args.volume),
                metadata=(("controllerid", args.controller),),
                timeout=60,
            ))
        elif args.command == "health":
            stub = REGISTRY.stub(channel)
            rows = []
            for value in rpc(lambda: stub.GetValues(
                oim_pb2.GetValuesRequest(path=health_states.HEALTH_PREFIX),
                timeout=30,
            )).values:
                parsed = health_states.parse_health_path(value.path)
                report = health_states.decode_report(value.value)
                if parsed is None or report is None:
                    continue
                rows.append((parsed[0], parsed[1], report))
            if rows:
                print(
                    f"{'CONTROLLER':<16} {'CHIP':<6} {'STATE':<10} "
                    f"{'LINK_ERRS':<10} ALLOCATION"
                )
                for cid, chip, report in sorted(
                    rows,
                    key=lambda r: (
                        r[0],
                        (0, int(r[1]), "") if r[1].isdigit() else (1, 0, r[1]),
                    ),
                ):
                    print(
                        f"{cid:<16} {chip:<6} {report['state']:<10} "
                        f"{report['link_errors']:<10} {report['allocation']}"
                    )
            else:
                print("no health telemetry (no reporting controllers)")
            for value in rpc(lambda: stub.GetValues(
                oim_pb2.GetValuesRequest(path=health_states.DRAIN_PREFIX),
                timeout=30,
            )).values:
                cid = health_states.parse_drain_path(value.path)
                if cid is not None and value.value:
                    print(f"cordoned: {cid} ({value.value})")
            for value in rpc(lambda: stub.GetValues(
                oim_pb2.GetValuesRequest(path=health_states.EVICTIONS_PREFIX),
                timeout=30,
            )).values:
                volume = health_states.parse_eviction_path(value.path)
                if volume is not None and value.value:
                    print(f"evicted: {volume} {value.value}")
        elif args.command == "drain":
            rpc(lambda: REGISTRY.stub(channel).SetValue(
                oim_pb2.SetValueRequest(
                    value=oim_pb2.Value(
                        path=health_states.drain_key(args.controller_id),
                        value=args.reason,
                    )
                ),
                timeout=30,
            ))
            print(f"cordoned {args.controller_id}")
        elif args.command == "uncordon":
            rpc(lambda: REGISTRY.stub(channel).SetValue(
                oim_pb2.SetValueRequest(
                    value=oim_pb2.Value(
                        path=health_states.drain_key(args.controller_id),
                        value="",
                    )
                ),
                timeout=30,
            ))
            print(f"uncordoned {args.controller_id}")
        elif args.command == "remap":
            stub = REGISTRY.stub(channel)
            path = health_states.eviction_key(args.volume)
            record = None
            for value in rpc(lambda: stub.GetValues(
                oim_pb2.GetValuesRequest(path=path), timeout=30
            )).values:
                if value.path == path and value.value:
                    try:
                        record = json.loads(value.value)
                    except ValueError:
                        record = {}
            if record is not None and not args.force:
                remap_after = float(record.get("remap_after") or 0.0)
                wait = remap_after - time.time()
                if wait > 0:
                    print(
                        f"error: {args.volume!r} is in its remap backoff "
                        f"for another {wait:.1f}s (use --force to override)"
                    )
                    return 1
            # Release the old placement first so the faulted controller's
            # chips free up and its telemetry stops claiming the volume
            # (idempotent; a DEAD controller is expected to be
            # unreachable — controller-dead evictions have nothing left
            # to unmap).
            old = (record or {}).get("controller", "")
            if old:
                try:
                    CONTROLLER.stub(channel).UnmapVolume(
                        oim_pb2.UnmapVolumeRequest(volume_id=args.volume),
                        metadata=(("controllerid", old),),
                        timeout=15,
                    )
                except grpc.RpcError as exc:
                    print(
                        f"note: unmap on old controller {old!r} failed "
                        f"({resilience.status_of(exc).name}); continuing"
                    )
            # Map BEFORE clearing the eviction mark: if the new placement
            # fails (ENOSPC, dead controller) the volume must stay
            # evicted, or a retried NodeStage would land it right back on
            # the faulted slice.
            print(f"remapping {args.volume} onto {args.controller}")
            _map_and_print(
                channel, args.volume, args.controller, args.chips, rpc=rpc
            )
            if record is not None:
                rpc(lambda: stub.SetValue(
                    oim_pb2.SetValueRequest(
                        value=oim_pb2.Value(path=path, value="")
                    ),
                    timeout=30,
                ))
            print(f"remapped {args.volume} onto {args.controller}")
        elif args.command == "events":
            # Registry-backed: the durable WARNING+ copies every daemon's
            # publisher mirrored under leased events/<source>/<seq> keys.
            from oim_tpu.common import events as events_mod

            def decode(value):
                if events_mod.parse_event_path(value.path) is None:
                    return None
                if not value.value:
                    return None  # deleted/TTL-expired
                try:
                    return events_mod.Event.from_json(json.loads(value.value))
                except (ValueError, TypeError):
                    return None  # foreign/torn value: skip, never crash

            def wanted(event):
                return event is not None and events_mod.filter_events(
                    [event], volume=args.volume,
                    component=args.component, kind=args.kind,
                )

            if args.follow:
                call = REGISTRY.stub(channel).WatchValues(
                    oim_pb2.WatchValuesRequest(
                        path=events_mod.EVENTS_PREFIX, send_initial=True
                    )
                )
                try:
                    for reply in call:
                        if reply.initial_done:
                            print("-- initial snapshot complete --", flush=True)
                            continue
                        event = decode(reply.value)
                        if wanted(event):
                            print(events_mod.render_event(event), flush=True)
                except KeyboardInterrupt:
                    call.cancel()
                except grpc.RpcError as exc:
                    if resilience.status_of(exc) != grpc.StatusCode.CANCELLED:
                        print(f"error: {resilience.error_text(exc)}")
                        return 1
            else:
                reply = rpc(lambda: REGISTRY.stub(channel).GetValues(
                    oim_pb2.GetValuesRequest(path=events_mod.EVENTS_PREFIX),
                    timeout=30,
                ))
                evts = [e for e in map(decode, reply.values) if e is not None]
                print(events_mod.render_timeline(
                    evts, volume=args.volume, component=args.component,
                    kind=args.kind,
                ))
        elif args.command == "top":
            # Registry mode (no router running): the same load/<cn>
            # keys the autoscaler's watch mirrors, plus serve/ for the
            # live backend set and autoscale/replicas/ for desired.
            from oim_tpu.autoscale.autoscaler import (
                REPLICA_PREFIX,
                ReplicaRecord,
                parse_replica_record_path,
            )
            from oim_tpu.autoscale.load import (
                LOAD_PREFIX,
                decode_load,
                parse_load_path,
            )

            stub = REGISTRY.stub(channel)

            def fetch_registry_top():
                loads: dict[str, dict] = {}
                live: set[str] = set()
                records = []
                try:
                    for value in rpc(lambda: stub.GetValues(
                        oim_pb2.GetValuesRequest(path=LOAD_PREFIX),
                        timeout=30,
                    )).values:
                        cn = parse_load_path(value.path)
                        if cn is None or not value.value:
                            continue
                        snap = decode_load(value.value)
                        if snap is not None:
                            loads[cn] = snap
                    for value in rpc(lambda: stub.GetValues(
                        oim_pb2.GetValuesRequest(path="serve"), timeout=30
                    )).values:
                        parts = value.path.split("/")
                        if (len(parts) == 3 and parts[0] == "serve"
                                and parts[2] == "address" and value.value):
                            live.add(f"serve.{parts[1]}")
                    for value in rpc(lambda: stub.GetValues(
                        oim_pb2.GetValuesRequest(path=REPLICA_PREFIX),
                        timeout=30,
                    )).values:
                        rid = parse_replica_record_path(value.path)
                        if rid is None or not value.value:
                            continue
                        record = ReplicaRecord.decode(rid, value.value)
                        if record is not None:
                            records.append(record)
                except grpc.RpcError as exc:
                    raise _TopUnavailable(resilience.error_text(exc))
                # HEALTHY = the discovery key exists: a health-withdrawn
                # backend (PR 6 gate) loses serve/<id>/address first
                # while its leased load key ages out — exactly the
                # backend being triaged must not print healthy.
                rows = [
                    (cn, cn in live, loads.get(cn, {}))
                    for cn in sorted(live | set(loads))
                ]
                line = ""
                if records:
                    states: dict[str, int] = {}
                    for record in records:
                        states[record.state] = (
                            states.get(record.state, 0) + 1
                        )
                    desired = sum(
                        n for s, n in states.items() if s != "draining"
                    )
                    detail = " ".join(
                        f"{s}={n}" for s, n in sorted(states.items())
                    )
                    line = (
                        f"autoscaler: desired {desired} vs live "
                        f"{len(live)} ({detail})"
                    )
                return rows, line

            return _run_top(args.watch, fetch_registry_top)
        elif args.command == "topology":
            reply = rpc(lambda: CONTROLLER.stub(channel).GetTopology(
                oim_pb2.GetTopologyRequest(),
                metadata=(("controllerid", args.controller),),
                timeout=30,
            ))
            print(
                f"chips={reply.chip_count} free={reply.free_chips} "
                f"mesh={list(reply.mesh.dims)} accel={reply.accel_type}"
            )
        elif args.command == "slices":
            reply = rpc(lambda: CONTROLLER.stub(channel).ListSlices(
                oim_pb2.ListSlicesRequest(),
                metadata=(("controllerid", args.controller),),
                timeout=30,
            ))
            for s in reply.slices:
                print(
                    f"{s.name}: chips={s.chip_count} mesh={list(s.mesh.dims)}"
                    f" provisioned={s.provisioned} attached={s.attached}"
                )
    except grpc.RpcError as exc:
        # error_text is None-code-safe (a locally raised RpcError would
        # otherwise crash the formatting here).
        print(f"error: {resilience.error_text(exc)}")
        return 1
    finally:
        channel.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
