"""oim-train: the end-to-end training binary.

Composes the whole compute stack the way a workload pod would: CSI-staged
bootstrap (or a local mesh) → 5-axis mesh → deterministic sharded token
batches with device prefetch → jitted train step (GPipe or 1F1B under pp)
→ async orbax checkpoints with exact data-cursor resume.  Re-running the
same command after an interruption continues from the latest checkpoint —
the trainer is idempotent the way every control-plane RPC is.

The reference framework has no trainer (it is a storage control plane);
this is the TPU build's user-facing surface for actually running work on
the slices the control plane provisions (SURVEY.md §2.3 TPU-build column).

Usage (smoke, CPU):
    JAX_PLATFORMS=cpu python -m oim_tpu.cli.train_main \\
        --synthetic 200000 --steps 50 --batch-global 8 --seq 128 \\
        --d-model 64 --n-layers 2 --n-heads 4 --checkpoint-dir /tmp/ckpt
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

from oim_tpu import log


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="oim-train", description=__doc__)
    data = p.add_mutually_exclusive_group(required=True)
    data.add_argument(
        "--corpus", help=".npy (or memmap-able) 1-D int32 token corpus"
    )
    data.add_argument(
        "--synthetic", type=int, metavar="N_TOKENS",
        help="deterministic synthetic corpus (smoke tests / benchmarks)",
    )
    p.add_argument("--steps", type=int, required=True)
    p.add_argument("--batch-global", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--seed", type=int, default=0)
    # Model geometry.
    p.add_argument("--vocab-size", type=int, default=32768)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--d-ff", type=int, default=0)
    p.add_argument("--n-experts", type=int, default=0)
    p.add_argument("--moe-top-k", type=int, default=1)
    p.add_argument(
        "--router-z-loss", type=float, default=0.0,
        help="ST-MoE router z-loss coefficient (paper value 1e-3); "
        "keeps router logits small on long MoE runs (0 = off)",
    )
    p.add_argument("--rope-theta", type=float, default=10000.0)
    p.add_argument(
        "--sliding-window", type=int, default=0,
        help="sliding-window attention: each token attends the last N "
        "positions (0 = full causal); oim-serve honors the same window",
    )
    p.add_argument(
        "--doc-sep-id", type=int, default=-1,
        help="sequence packing: treat this token id as a document "
        "separator (attention masked to same-document pairs, boundary "
        "labels dropped); -1 = off",
    )
    p.add_argument(
        "--rope-scaling", type=float, nargs=4, default=[],
        metavar=("FACTOR", "LOW", "HIGH", "ORIG_MAX"),
        help="Llama-3.1 RoPE frequency remap (factor low_freq_factor "
        "high_freq_factor original_max_position); omit for plain RoPE",
    )
    p.add_argument(
        "--norm-eps", type=float, default=1e-6,
        help="RMSNorm epsilon (imported HF Llama checkpoints use 1e-5)",
    )
    p.add_argument(
        "--attn-bias", action="store_true",
        help="q/k/v projection biases (Qwen2-family imports)",
    )
    p.add_argument(
        "--mlp-act", default="silu", choices=["silu", "gelu_tanh"],
        help="MLP gate activation (gelu_tanh = Gemma GeGLU)",
    )
    p.add_argument(
        "--norm-offset", action="store_true",
        help="RMSNorm scales by (1 + weight) (Gemma family)",
    )
    p.add_argument(
        "--embed-scale", action="store_true",
        help="scale embeddings by sqrt(d_model) (Gemma family)",
    )
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--attn-impl", default="ring", choices=["ring", "ulysses"])
    p.add_argument("--pp-schedule", default="gpipe", choices=["gpipe", "1f1b"])
    p.add_argument("--n-microbatches", type=int, default=1)
    # Mesh: explicit axes, or inferred from the CSI-staged bootstrap.
    p.add_argument("--dp", type=int, default=0, help="0 = use all remaining")
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument(
        "--bootstrap", default="",
        help="tpu-bootstrap.json path (default: $TPU_BOOTSTRAP when set)",
    )
    # Optimization + lifecycle.
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument(
        "--warmup-steps", type=_nonneg_int, default=0,
        help="linear warmup; with --decay-steps forms warmup+cosine",
    )
    p.add_argument(
        "--decay-steps", type=_nonneg_int, default=0,
        help="cosine-decay horizon after warmup (0 = constant lr)",
    )
    p.add_argument(
        "--grad-clip", type=float, default=0.0,
        help="global-norm gradient clip (0 = off)",
    )
    p.add_argument(
        "--zero1", action="store_true",
        help="ZeRO-1: shard adamw moments over the dp axis (optimizer "
        "memory / dp; math unchanged — the update all-gathers params)",
    )
    p.add_argument(
        "--grad-accum", type=_positive_int, default=1,
        help="sequential microbatches averaged per optimizer step "
        "(peak activation memory / N at the same global batch)",
    )
    # LoRA fine-tuning: freeze a base params export, train adapters only.
    p.add_argument(
        "--lora-rank", type=_nonneg_int, default=0,
        help="low-rank adapter rank over wq/wk/wv/wo (0 = full training)",
    )
    p.add_argument("--lora-alpha", type=float, default=16.0)
    p.add_argument(
        "--lora-base", default="",
        help="frozen base weights: a params export (oim-train --export-dir)",
    )
    p.add_argument(
        "--export-dir", default="",
        help="after training, export params-only (no optimizer state) "
        "for oim-serve --params-dir",
    )
    # Held-out evaluation: the corpus tail is split off for validation.
    p.add_argument(
        "--eval-every", type=_nonneg_int, default=0,
        help="run held-out eval every N steps (0 = off)",
    )
    p.add_argument(
        "--eval-frac", type=float, default=0.05,
        help="fraction of the corpus tail held out for eval",
    )
    p.add_argument(
        "--eval-batches", type=_positive_int, default=4,
        help="batches averaged per eval pass",
    )
    p.add_argument(
        "--weight-decay", type=float, default=1e-4,
        help="adamw decay on matmul weights (norm gains are excluded)",
    )
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument(
        "--save-every", type=_positive_int, default=200,
        help="checkpoint interval in steps (>= 1)",
    )
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--log-level", default="info")
    return p


def _positive_int(value: str) -> int:
    n = int(value)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def _nonneg_int(value: str) -> int:
    n = int(value)
    if n < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0, got {n}")
    return n


def _load_corpus(args) -> np.ndarray:
    if args.corpus:
        tokens = np.load(args.corpus, mmap_mode="r")
        return tokens
    rng = np.random.default_rng(args.seed)
    # Markov-ish ramp so the loss visibly falls on smoke runs.
    base = rng.integers(0, args.vocab_size, size=args.synthetic // 8)
    ramp = (base[:, None] + np.arange(8)[None, :]) % args.vocab_size
    return ramp.reshape(-1).astype(np.int32)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    log.init_from_string(args.log_level)
    if args.export_dir and not args.checkpoint_dir:
        # Validate up front — discovering this after hours of training
        # (or masking a mid-run exception from inside finally) is not ok.
        raise SystemExit("--export-dir requires --checkpoint-dir")
    if args.lora_rank and not args.lora_base:
        raise SystemExit("--lora-rank requires --lora-base (a params export)")
    if args.lora_base and not args.lora_rank:
        # Silently training from random init while the operator believes
        # they are fine-tuning the given base would be hours wasted.
        raise SystemExit("--lora-base requires --lora-rank >= 1")

    import jax

    from oim_tpu.data.loader import ShardSpec, TokenBatches
    from oim_tpu.data.prefetch import device_prefetch
    from oim_tpu.models import (
        TrainState,
        TransformerConfig,
        init_params,
        make_train_step,
    )
    from oim_tpu.models.train import data_pspec
    from oim_tpu.parallel import build_mesh, mesh_from_bootstrap
    from oim_tpu.parallel.coordinator import (
        apply_chip_binding,
        initialize_distributed,
        load_bootstrap,
    )

    bootstrap_path = args.bootstrap or os.environ.get("TPU_BOOTSTRAP", "")
    axes = dict(pp=args.pp, sp=args.sp, tp=args.tp, ep=args.ep)
    if bootstrap_path:
        bootstrap = load_bootstrap(bootstrap_path)
        apply_chip_binding(bootstrap)
        initialize_distributed(bootstrap)
        log.current().info(
            "bootstrap loaded", volume=bootstrap.volume_id,
            chips=bootstrap.chip_count,
            process=f"{bootstrap.process_id}/{bootstrap.num_processes}",
        )
        # mesh_from_bootstrap infers dp from the slice's chip count and
        # errors on non-dividing axis products (no silently idle chips).
        mesh = mesh_from_bootstrap(bootstrap, dp=args.dp, **axes)
    else:
        n = jax.device_count()
        fixed = args.pp * args.sp * args.tp * args.ep
        dp = args.dp or n // fixed
        if not args.dp and dp * fixed != n:
            # Inferred dp flooring would silently idle chips; make the
            # operator choose.
            raise SystemExit(
                f"{n} devices not divisible by pp*sp*tp*ep={fixed}; pass "
                "--dp explicitly (a sub-mesh is allowed when explicit)"
            )
        if dp * fixed < n:
            log.current().warning(
                "mesh uses a subset of devices",
                used=dp * fixed, available=n,
            )
        mesh = build_mesh(dp=dp, **axes)
    log.current().info("mesh", shape=str(dict(mesh.shape)))

    cfg = TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        attn_bias=args.attn_bias,
        mlp_act=args.mlp_act,
        norm_offset=args.norm_offset,
        embed_scale=args.embed_scale,
        d_ff=args.d_ff,
        n_experts=args.n_experts,
        moe_top_k=args.moe_top_k,
        router_z_loss=args.router_z_loss,
        rope_theta=args.rope_theta,
        rope_scaling=tuple(args.rope_scaling),
        norm_eps=args.norm_eps,
        sliding_window=args.sliding_window,
        doc_sep_id=args.doc_sep_id,
        n_stages=args.pp,
        n_microbatches=max(args.n_microbatches, 1),
        grad_accum=args.grad_accum,
        dtype=args.dtype,
        attn_impl=args.attn_impl,
        pp_schedule=args.pp_schedule,
    )

    import optax

    if args.decay_steps:
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=args.lr,
            warmup_steps=max(args.warmup_steps, 1),
            # optax counts warmup INSIDE decay_steps; the flag's contract
            # is "horizon after warmup".
            decay_steps=args.warmup_steps + args.decay_steps,
        )
    elif args.warmup_steps:
        lr = optax.linear_schedule(0.0, args.lr, args.warmup_steps)
    else:
        lr = args.lr
    optimizer = optax.adamw(
        lr,
        weight_decay=args.weight_decay,
        # Standard practice: decay matmul weights, never norm gains.
        mask=lambda params: {
            name: not name.endswith("_norm") for name in params
        },
    )
    if args.grad_clip > 0:
        optimizer = optax.chain(
            optax.clip_by_global_norm(args.grad_clip), optimizer
        )

    lora_base = None
    if args.lora_rank:
        from oim_tpu.checkpoint import load_params
        from oim_tpu.models.lora import init_lora

        template = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg)
        )
        lora_base = load_params(args.lora_base, template, cfg, mesh)
        log.current().info(
            "lora", rank=args.lora_rank, alpha=args.lora_alpha,
            base=args.lora_base,
        )

    def init_fn() -> TrainState:
        if args.lora_rank:
            return TrainState.create(
                init_lora(
                    jax.random.PRNGKey(args.seed), cfg, args.lora_rank
                ),
                optimizer,
            )
        return TrainState.create(
            init_params(jax.random.PRNGKey(args.seed), cfg), optimizer
        )

    start_step = 0
    checkpointer = None
    if args.checkpoint_dir:
        from oim_tpu.checkpoint import Checkpointer, CheckpointerOptions

        checkpointer = Checkpointer(
            args.checkpoint_dir, cfg, mesh,
            options=CheckpointerOptions(save_interval_steps=args.save_every),
            zero1=args.zero1,
        )
        state, data_state, resumed = checkpointer.restore_or_init(init_fn)
        if resumed:
            # The data cursor is authoritative for the token stream; it
            # equals state.step in this trainer, but consuming it keeps the
            # checkpoint package's resume contract honest.
            start_step = int(
                (data_state or {}).get(
                    "next_step", jax.device_get(state.step)
                )
            )
            log.current().info("resumed", step=start_step)
    else:
        from oim_tpu.models.train import shard_state

        state = shard_state(init_fn(), cfg, mesh, zero1=args.zero1)

    tokens = _load_corpus(args)
    shard = ShardSpec(jax.process_index(), jax.process_count())
    sharding = jax.sharding.NamedSharding(mesh, data_pspec())

    eval_fn = None
    if args.eval_every:
        from oim_tpu.data.loader import window_count
        from oim_tpu.models import make_eval_step

        if not 0.0 < args.eval_frac < 1.0:
            raise SystemExit(
                f"--eval-frac must be in (0, 1), got {args.eval_frac}"
            )
        n_eval = int(len(tokens) * args.eval_frac)
        if window_count(n_eval, args.seq) < args.batch_global:
            raise SystemExit(
                f"eval split of {n_eval} tokens cannot fill one "
                f"batch of {args.batch_global}x(seq+1); raise --eval-frac "
                "or use a larger corpus"
            )
        # Tail split: train never sees the eval tokens.
        eval_tokens, tokens = tokens[len(tokens) - n_eval:], tokens[
            : len(tokens) - n_eval
        ]
        eval_batches = TokenBatches(
            eval_tokens, args.batch_global, args.seq, shard,
            seed=args.seed + 1,
        )
        eval_step = make_eval_step(cfg, mesh)
        # Distinct windows only: reading past one epoch would re-average
        # the same windows and misrepresent the batch count.
        n_eval_batches = min(args.eval_batches, eval_batches.steps_per_epoch)
        if n_eval_batches < args.eval_batches:
            log.current().warning(
                "eval split smaller than requested batches; clamping",
                requested=args.eval_batches, used=n_eval_batches,
            )

        def eval_fn(params) -> float:
            from oim_tpu.data.prefetch import to_global

            ces = [
                eval_step(
                    params,
                    # to_global, not device_put: each process holds only
                    # its shard of the batch (same as the train path).
                    to_global(
                        eval_batches.batch_at(i)[:, : args.seq], sharding
                    ),
                )
                for i in range(n_eval_batches)
            ]
            return float(np.mean([jax.device_get(c) for c in ces]))

    batches = TokenBatches(
        tokens, args.batch_global, args.seq, shard, seed=args.seed
    )

    def batch_stream():
        step = start_step
        while step < args.steps:
            # [b, seq+1] windows; the train step derives labels itself
            # from a [b, seq] input, so the window's +1 boundary token is
            # dropped — its LABEL role is lost (1/seq of supervision), a
            # deliberate trade for shard-divisible static shapes.
            yield batches.batch_at(step)[:, : args.seq]
            step += 1

    if args.lora_rank:
        from oim_tpu.models.lora import make_lora_train_step

        lora_step = make_lora_train_step(
            cfg, mesh, optimizer, args.lora_alpha, args.lora_rank
        )
        step_fn = lambda state, batch: lora_step(state, lora_base, batch)  # noqa: E731
    else:
        step_fn = make_train_step(cfg, mesh, optimizer)
    t0 = time.perf_counter()
    window_tokens = 0
    step = start_step
    try:
        for device_batch in device_prefetch(batch_stream(), sharding):
            state, metrics = step_fn(state, device_batch)
            step += 1
            window_tokens += args.batch_global * args.seq
            if step % args.log_every == 0 or step == args.steps:
                loss = float(jax.device_get(metrics["loss"]))
                dt = time.perf_counter() - t0
                log.current().info(
                    "step", step=step, loss=round(loss, 4),
                    tok_per_s=round(window_tokens / max(dt, 1e-9)),
                )
                t0, window_tokens = time.perf_counter(), 0
            if eval_fn is not None and (
                step % args.eval_every == 0 or step == args.steps
            ):
                if args.lora_rank:
                    from oim_tpu.models.lora import merge_lora

                    eval_params = merge_lora(
                        lora_base, state.params, args.lora_alpha,
                        args.lora_rank,
                    )
                else:
                    eval_params = state.params
                ce = eval_fn(eval_params)
                log.current().info(
                    "eval", step=step, eval_ce=round(ce, 4),
                    eval_ppl=round(float(np.exp(min(ce, 30.0))), 2),
                )
            # Gate host-side: Checkpointer.save device_gets state.step
            # (a per-step host sync would serialize dispatch against the
            # async prefetch for nothing on off-interval steps).
            if checkpointer is not None and step % args.save_every == 0:
                checkpointer.save(state, {"next_step": step})
    finally:
        if checkpointer is not None:
            try:
                # The train step donates the state buffers: after an
                # exception mid-step the arrays are deleted and a rescue
                # save would mask the root cause — save only when alive.
                leaves = jax.tree_util.tree_leaves(state)
                alive = bool(leaves) and not leaves[0].is_deleted()
                if alive and checkpointer.latest_step() != step:
                    checkpointer.save(state, {"next_step": step}, force=True)
                if alive and args.export_dir and step >= args.steps:
                    # Completed runs only: a crash mid-train must not
                    # leave partial weights at the export path.  An
                    # existing export means a prior completed run already
                    # wrote it (orbax renames atomically): re-running the
                    # same command must stay idempotent, not crashloop.
                    if os.path.exists(args.export_dir):
                        log.current().info(
                            "export exists; skipping", dir=args.export_dir
                        )
                    elif args.lora_rank:
                        # Export the MERGED weights: serving needs no LoRA
                        # support, and downstream fine-tunes can re-base.
                        from oim_tpu.models.lora import merge_lora

                        checkpointer.export_params(
                            TrainState(
                                params=merge_lora(
                                    lora_base, state.params,
                                    args.lora_alpha, args.lora_rank,
                                ),
                                opt_state=None,
                                step=state.step,
                            ),
                            args.export_dir,
                        )
                    else:
                        checkpointer.export_params(state, args.export_dir)
            finally:
                checkpointer.close()  # always await queued async saves
    log.current().info("done", steps=step)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
