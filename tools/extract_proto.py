#!/usr/bin/env python3
"""Extract the protobuf source from the literate spec.

≙ the reference's Makefile extraction of spec.md fenced blocks into
oim.proto (reference Makefile:85-103).  Concatenates every ```protobuf block
of doc/spec.md, in order, into proto/oim/v1/oim.proto.  With --check, exits
nonzero if the committed file differs (the CI sync gate).
"""

import argparse
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPEC = os.path.join(ROOT, "doc", "spec.md")
OUT = os.path.join(ROOT, "proto", "oim", "v1", "oim.proto")

HEADER = """\
// Code generated from doc/spec.md by tools/extract_proto.py. DO NOT EDIT.
//
// The literate spec is the source of truth; run `make gen` after editing it.

"""


def extract() -> str:
    with open(SPEC) as f:
        text = f.read()
    blocks = re.findall(r"```protobuf\n(.*?)```", text, re.DOTALL)
    if not blocks:
        raise SystemExit(f"no ```protobuf blocks found in {SPEC}")
    return HEADER + "\n".join(b.rstrip() + "\n" for b in blocks)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--check", action="store_true")
    args = parser.parse_args()
    content = extract()
    if args.check:
        try:
            with open(OUT) as f:
                current = f.read()
        except FileNotFoundError:
            current = ""
        if current != content:
            print(f"{OUT} is out of sync with {SPEC}; run `make gen`",
                  file=sys.stderr)
            return 1
        return 0
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write(content)
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
