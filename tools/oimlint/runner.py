"""oimvet runner: pass orchestration, the baseline gate, the CLI."""

from __future__ import annotations

import argparse
import time

from tools.oimlint import core
from tools.oimlint.core import Finding, SourceTree
from tools.oimlint.passes import ALL_PASSES


def run_passes(
    tree: SourceTree | None = None, pass_ids: list[str] | None = None
) -> list[Finding]:
    """All (or the selected) passes over ``tree``; waivers applied,
    parse errors included as findings."""
    if tree is None:
        tree = SourceTree()
    ids = pass_ids if pass_ids is not None else list(ALL_PASSES)
    findings: list[Finding] = []
    for pass_id in ids:
        if pass_id not in ALL_PASSES:
            raise SystemExit(
                f"oimlint: unknown pass {pass_id!r} "
                f"(known: {', '.join(ALL_PASSES)})"
            )
        findings.extend(ALL_PASSES[pass_id].run(tree))
    findings.extend(tree.parse_errors)
    kept, _waived = core.apply_waivers(tree, findings)
    return kept


def gate(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], set[str]]:
    """(new findings, stale baseline keys)."""
    keys = {f.key() for f in findings}
    new = [f for f in findings if f.key() not in baseline]
    stale = baseline - keys
    return new, stale


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.oimlint",
        description="oimvet: OIM-TPU control-plane static analyzer",
    )
    parser.add_argument(
        "--passes",
        help="comma-separated pass ids (default: all)",
    )
    parser.add_argument(
        "--repo",
        default=core.REPO,
        help="tree root to scan (default: this repo; used by the "
        "analyzer's own tests to point passes at fixture snippets)",
    )
    parser.add_argument(
        "--roots",
        default="oim_tpu",
        help="comma-separated repo-relative directories to walk "
        "(default: oim_tpu)",
    )
    parser.add_argument(
        "--baseline",
        default=core.DEFAULT_BASELINE,
        help="baseline file (default: tools/oimlint/baseline.txt)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to the current findings and exit 0",
    )
    parser.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="suppress the summary line"
    )
    args = parser.parse_args(argv)

    if args.list_passes:
        for pass_id, mod in ALL_PASSES.items():
            print(f"{pass_id:<20} {mod.DESCRIPTION}")
        return 0

    t0 = time.monotonic()
    pass_ids = (
        [p.strip() for p in args.passes.split(",") if p.strip()]
        if args.passes
        else None
    )
    roots = tuple(r for r in (s.strip() for s in args.roots.split(",")) if r)
    tree = SourceTree(repo=args.repo, roots=roots or ("oim_tpu",))
    findings = run_passes(tree, pass_ids=pass_ids)

    if args.update_baseline:
        core.write_baseline(args.baseline, findings)
        print(
            f"oimlint: baseline updated with {len(findings)} finding(s) "
            f"→ {args.baseline}"
        )
        return 0

    baseline = set() if args.no_baseline else core.load_baseline(args.baseline)
    # A pass subset must not treat the other passes' baseline entries as
    # stale — scope the baseline to the passes that actually ran.
    if pass_ids is not None:
        baseline = {
            k for k in baseline if k.split(" ", 1)[0] in set(pass_ids)
        }
    new, stale = gate(findings, baseline)
    for finding in sorted(new, key=lambda f: (f.file, f.line)):
        print(finding.render())
    # A stale entry is a FAILURE, not a note (ISSUE 19 CI hygiene): a
    # baseline line whose finding no longer exists means somebody fixed
    # the issue without shrinking the debt ledger — left in place it
    # masks the next regression at the same key.
    if stale:
        for key in sorted(stale):
            print(f"oimlint: stale baseline entry (finding fixed): {key}")
        print(
            "oimlint: run --update-baseline to drop "
            f"{len(stale)} fixed entr{'y' if len(stale) == 1 else 'ies'}"
        )
    if not args.quiet:
        dt = time.monotonic() - t0
        print(
            f"oimlint: {len(new)} new finding(s), "
            f"{len(findings) - len(new)} baselined, "
            f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}, "
            f"{len(ALL_PASSES) if pass_ids is None else len(pass_ids)} "
            f"pass(es) in {dt:.1f}s"
        )
    return 1 if new or stale else 0
