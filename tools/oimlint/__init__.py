"""oimvet — the OIM-TPU control-plane static analyzer.

``python -m tools.oimlint`` / ``make lint`` runs six AST-level passes
over ``oim_tpu/`` (lock-discipline, resource-lifecycle, authz-coverage,
protocol-drift, deadline-hygiene, metrics) and fails on any finding
that is neither waived in code (``# oimlint: disable=<pass>``) nor
grandfathered in ``tools/oimlint/baseline.txt``.  See
doc/development.md "The oimvet static analyzer".
"""

from tools.oimlint.core import (  # noqa: F401
    DEFAULT_BASELINE,
    Finding,
    SourceTree,
    apply_waivers,
    load_baseline,
    write_baseline,
)
from tools.oimlint.runner import gate, main, run_passes  # noqa: F401
