"""metrics: every registered series ``oim_``-prefixed with non-empty HELP.

The former ``tools/check_metrics.py``, folded into oimlint so there is
one analyzer (``tools/check_metrics.py`` remains as a thin alias).  Two
sub-checks, both fast and stdlib-only:

1. **Source scan** (AST): every ``.counter("name", "help", ...)`` /
   ``.gauge(...)`` / ``.histogram(...)`` call whose name is a string
   literal — catches instruments registered at instance-construction
   time, which a runtime import can never see.
2. **Runtime check**: import the always-importable metrics-defining
   modules (no jax required) and validate what actually landed in the
   process registry — catches dynamically built names the AST pass
   skips.  Skipped when the scanned tree is not the real repo (fixture
   runs).
"""

from __future__ import annotations

import ast
import os

from tools.oimlint.core import REPO, Finding, SourceTree

PASS_ID = "metrics"
DESCRIPTION = "metric series are oim_-prefixed with non-empty HELP"

REGISTER_METHODS = {"counter", "gauge", "histogram"}


def _scan_file(tree: SourceTree, rel: str) -> list[Finding]:
    mod = tree.tree(rel)
    if mod is None:
        return []
    problems: list[Finding] = []
    for node in ast.walk(mod):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr in REGISTER_METHODS):
            continue
        if not node.args:
            continue
        name_node = node.args[0]
        if not (isinstance(name_node, ast.Constant) and isinstance(name_node.value, str)):
            continue  # dynamic name: left to the runtime check
        name = name_node.value
        if not name.startswith("oim_"):
            problems.append(
                Finding(
                    PASS_ID, rel, node.lineno,
                    f"series {name!r} is not 'oim_'-prefixed",
                )
            )
        help_node = node.args[1] if len(node.args) > 1 else None
        if isinstance(help_node, ast.Constant) and isinstance(help_node.value, str):
            if not help_node.value.strip():
                problems.append(
                    Finding(
                        PASS_ID, rel, node.lineno,
                        f"series {name!r} has empty HELP",
                    )
                )
        elif isinstance(help_node, ast.JoinedStr):
            pass  # f-string help: non-empty by construction
        elif help_node is None and "help_" not in {
            kw.arg for kw in node.keywords
        }:
            problems.append(
                Finding(
                    PASS_ID, rel, node.lineno,
                    f"series {name!r} has no HELP argument",
                )
            )
    return problems


def _check_runtime() -> list[Finding]:
    # The jax-free metrics definers; jax-importing modules (data,
    # checkpoint, serve engine) are covered by the source scan.
    import oim_tpu.common.events  # noqa: F401
    import oim_tpu.common.metrics as metrics
    import oim_tpu.common.resilience  # noqa: F401
    import oim_tpu.common.tracing  # noqa: F401

    problems: list[Finding] = []
    for name, metric in sorted(metrics.registry()._metrics.items()):
        if not name.startswith("oim_"):
            problems.append(
                Finding(
                    PASS_ID, "(runtime registry)", 0,
                    f"series {name!r} not 'oim_'-prefixed",
                )
            )
        if not str(getattr(metric, "help", "")).strip():
            problems.append(
                Finding(
                    PASS_ID, "(runtime registry)", 0,
                    f"series {name!r} has empty HELP",
                )
            )
    return problems


def run(tree: SourceTree, runtime: bool | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for rel in tree.files():
        findings.extend(_scan_file(tree, rel))
    if runtime is None:
        runtime = os.path.abspath(tree.repo) == os.path.abspath(REPO)
    if runtime:
        findings.extend(_check_runtime())
    return findings
