"""host-sync-discipline: no hidden device→host syncs on the hot path.

Every device→host fetch in the serve engine goes through the
``_fetch``/``_fetch_aux`` readback accumulators (PR 5): the wall time
blocked in ``jax.device_get`` is *attributed* — dispatch-wait vs
fetch-wait — which is what keeps the serving-swing forensics truthful.
An implicit sync (``float()`` on a jit result, ``.item()``,
``np.asarray`` on a device value, a raw ``device_get``) blocks the
driver thread the same way but books the wait as host time, *and*
serializes the pipelined dispatch-ahead overlap the bench trajectory is
built on.

Hot-path functions are designated with a ``# oimlint: hotpath`` marker
on (or above) the ``def`` line, or via ``jaxsites.HOTPATH_TABLE``.
Inside them this pass flags:

1. raw ``jax.device_get(...)`` / ``x.block_until_ready()`` — every
   readback must ride the accumulator (``self._fetch`` /
   ``self._fetch_aux``), which is exempt by construction because the
   accumulators themselves are not hot-path-marked;
2. ``float()/int()/bool()`` on a *device value* — a value produced by a
   jitted binding (shared resolver) or a ``jnp.*``/``jax.random.*``/
   ``jax.lax.*`` call, tracked through assignments, tuple unpacking,
   subscripts, and arithmetic; values from the accumulators,
   ``np.*``, or plain Python stay host-side and are never flagged;
3. ``.item()`` / ``.tolist()`` / ``np.asarray()/np.array()`` on a
   device value — same sync, different spelling;
4. a **constant device array rebuilt per call** — ``jax.random.
   PRNGKey(0)``, ``jnp.zeros/ones/full/arange/asarray`` with all-literal
   arguments: each call re-dispatches the same tiny host→device
   transfer every chunk; hoist it to ``__init__``.  Suppressed inside
   jit-wrapped bodies, where the constant folds into the trace and
   costs nothing per call.

Taint deliberately does NOT flow through arbitrary calls (``zip``,
helper methods): a device value laundered through one is missed
(under-approximation) rather than poisoning everything it touches
(false positives on the fetched-value paths the engine is full of).
"""

from __future__ import annotations

import ast

from tools.oimlint.core import Finding, SourceTree, dotted
from tools.oimlint.passes import jaxsites

PASS_ID = "host-sync-discipline"
DESCRIPTION = "hot-path device readbacks must ride the _fetch accumulator"

# The sanctioned readback accumulators: calls to these produce HOST
# values and are the only legal device_get spelling on the hot path.
ACCUMULATORS = {"self._fetch", "self._fetch_aux"}

_RAW_SYNCS = {"jax.device_get", "jax.block_until_ready"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_SYNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_CASTS = {"float", "int", "bool"}

_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.", "jax.lax.")
_CONST_BUILDERS = {
    "jax.random.PRNGKey", "jnp.zeros", "jnp.ones", "jnp.full",
    "jnp.arange", "jnp.asarray", "jnp.array",
}
# Attribute reads that stay host-side even on a device value.
_HOST_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _is_const_expr(node: ast.expr) -> bool:
    """Literal-only expression (ints, floats, strings, tuples/lists of
    them), plus dtype attributes (``jnp.int32``) — everything whose
    value cannot change between calls."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_const_expr(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_const_expr(node.operand)
    if isinstance(node, ast.Attribute):
        root = dotted(node) or ""
        return root.split(".")[0] in ("jnp", "np", "numpy", "jax")
    return False


class _Taint:
    """Per-function device-value taint over dotted names."""

    def __init__(self, jit_bindings: set[str]):
        self.jit_bindings = jit_bindings
        self.tainted: set[str] = set()

    def expr_tainted(self, node: ast.expr) -> bool:
        if isinstance(node, (ast.Name, ast.Attribute)):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _HOST_ATTRS
            ):
                return False
            name = dotted(node)
            if name in self.tainted:
                return True
            # self._cache.k is device iff self._cache is.
            if isinstance(node, ast.Attribute):
                return self.expr_tainted(node.value)
            return False
        if isinstance(node, ast.Call):
            callee = dotted(node.func) or ""
            if callee in ACCUMULATORS or callee in _RAW_SYNCS:
                return False  # device_get result is host-side
            if callee in self.jit_bindings:
                return True
            if callee.startswith(_DEVICE_PREFIXES) or callee in (
                "jax.device_put",
            ):
                return True
            return False  # arbitrary calls do not propagate taint
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.expr_tainted(node.left) or self.expr_tainted(
                node.right
            )
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.expr_tainted(node.body) or self.expr_tainted(
                node.orelse
            )
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value)
        return False

    def assign(self, targets: list[ast.expr], value: ast.expr) -> None:
        tainted = self.expr_tainted(value)
        for target in targets:
            elts = (
                target.elts
                if isinstance(target, (ast.Tuple, ast.List))
                else [target]
            )
            for elt in elts:
                if isinstance(elt, ast.Starred):
                    elt = elt.value
                name = dotted(elt)
                if name is None:
                    continue
                if tainted:
                    self.tainted.add(name)
                else:
                    self.tainted.discard(name)


def _check_hot_function(
    rel: str,
    fn: ast.FunctionDef,
    jit_bindings: set[str],
    in_jit_body: bool,
) -> list[Finding]:
    findings: list[Finding] = []
    taint = _Taint(jit_bindings)

    def scan(node: ast.AST) -> None:
        for stmt in node.body if hasattr(node, "body") else []:
            visit(stmt)

    def visit(stmt: ast.stmt) -> None:
        for expr in _own_exprs(stmt):
            if isinstance(expr, ast.Call):
                check_call(expr)
        if isinstance(stmt, ast.Assign):
            taint.assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            taint.assign([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            if taint.expr_tainted(stmt.value):
                name = dotted(stmt.target)
                if name:
                    taint.tainted.add(name)
        elif isinstance(stmt, ast.For):
            if taint.expr_tainted(stmt.iter):
                taint.assign([stmt.target], stmt.iter)
        # Recurse into child statements in document order.
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                visit(child)
            elif hasattr(child, "body") and isinstance(
                child, (ast.ExceptHandler,)
            ):
                for s in child.body:
                    visit(s)

    def check_call(call: ast.Call) -> None:
        callee = dotted(call.func) or ""
        last = callee.split(".")[-1]

        if callee in _RAW_SYNCS or (
            last == "block_until_ready" and callee not in ACCUMULATORS
        ):
            findings.append(Finding(
                PASS_ID, rel, call.lineno,
                f"{fn.name}: raw device→host sync {last}(...) on the hot "
                "path bypasses the _fetch/_fetch_aux readback accumulator "
                "(dispatch-wait vs fetch-wait attribution breaks)",
            ))
            return

        if (
            callee in _CASTS
            and len(call.args) == 1
            and taint.expr_tainted(call.args[0])
        ):
            findings.append(Finding(
                PASS_ID, rel, call.lineno,
                f"{fn.name}: {callee}() on a device value forces an "
                "implicit blocking sync — fetch it through "
                "self._fetch/_fetch_aux first",
            ))
            return

        if (
            last in _SYNC_METHODS
            and isinstance(call.func, ast.Attribute)
            and taint.expr_tainted(call.func.value)
        ):
            findings.append(Finding(
                PASS_ID, rel, call.lineno,
                f"{fn.name}: .{last}() on a device value forces an "
                "implicit blocking sync — fetch it through "
                "self._fetch/_fetch_aux first",
            ))
            return

        if (
            callee in _NP_SYNCS
            and call.args
            and taint.expr_tainted(call.args[0])
        ):
            findings.append(Finding(
                PASS_ID, rel, call.lineno,
                f"{fn.name}: {callee}() on a device value forces an "
                "implicit blocking sync — fetch it through "
                "self._fetch/_fetch_aux first",
            ))
            return

        if (
            not in_jit_body
            and callee in _CONST_BUILDERS
            and call.args
            and all(_is_const_expr(a) for a in call.args)
            and all(
                kw.value is not None and _is_const_expr(kw.value)
                for kw in call.keywords
            )
        ):
            findings.append(Finding(
                PASS_ID, rel, call.lineno,
                f"{fn.name}: constant device array {callee}(...) rebuilt "
                "on every hot-path call — hoist it to __init__ and reuse",
            ))

    scan(fn)
    return findings


def _own_exprs(stmt: ast.stmt):
    """Expression nodes of one statement, not descending into child
    statements (those are visited separately, in order, with the taint
    state they actually execute under)."""
    stack: list[ast.AST] = [
        c for c in ast.iter_child_nodes(stmt) if not isinstance(c, ast.stmt)
    ]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(
            c for c in ast.iter_child_nodes(node)
            if not isinstance(c, ast.stmt)
        )


def run(
    tree: SourceTree,
    table: dict[str, tuple[str, ...]] | None = None,
) -> list[Finding]:
    findings: list[Finding] = []
    factories = jaxsites.tree_factories(tree)
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        hot = jaxsites.hotpath_functions(tree, rel, table)
        if not hot:
            continue
        sites = jaxsites.resolve(tree, rel, factories)
        jit_bindings = set(sites.by_binding)
        jit_targets = {
            s.target for s in sites.all_sites if s.target
        }
        for name, fn in hot.items():
            findings.extend(_check_hot_function(
                rel, fn, jit_bindings, in_jit_body=name in jit_targets
            ))
    return findings
