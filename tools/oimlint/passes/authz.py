"""authz-coverage: every registry write path must have an authz grant.

The registry's write authorization is the declarative table
``oim_tpu.registry.authz.AUTHZ_GRANTS`` (which also drives enforcement,
so it cannot drift from the server).  This pass finds every *write
site* in the tree — ``SetValue`` payload construction
(``oim_pb2.Value(path=..., ...)``) and registry-embedded direct stores
(``self.db.store(path, ...)``) — resolves the path expression into a
segment pattern, and checks it against the grants for the identity the
writing module runs as.  A new ``put`` path without a grant fails lint
before it fails with PERMISSION_DENIED in production.

Path resolution (all static, tuned to this tree's idioms):

- f-strings: interpolations of the writer's own-identity expression
  (e.g. ``self.controller_id``) become ``{own}``; anything else becomes
  ``*`` (one segment);
- ``Name`` parts resolve through local ``x = "..."``/``x = f"..."``
  assignments and module-level string constants;
- calls to key-helper functions (``states.health_key(...)``,
  ``event_key(...)``, ``hosts_path(...)``) are inlined — including
  across modules resolved via the importing file's ``import``
  statements — with call arguments substituted for parameters;
- a path that is a bare function *parameter* (``def _set(channel,
  path, value)``) is resolved at that function's call sites instead.

Writers are declared in :data:`WRITERS` below: the CN template the
module authenticates as and which expressions are its own identity.
``ADMIN`` writers (operator CLI) match the admin ``**`` grant;
``REGISTRY_SIDE`` writers run inside the registry process and store
directly into the DB below the authz layer.  A registry write in a
module with no entry is itself a finding — add the writer (and a
grant) deliberately, not by accident.
"""

from __future__ import annotations

import ast

from tools.oimlint.core import Finding, SourceTree, dotted

PASS_ID = "authz-coverage"
DESCRIPTION = "registry write paths must match an AUTHZ_GRANTS row"

OWN = "{own}"
STAR = "*"
UNKNOWN = "{?}"

ADMIN = "admin"
REGISTRY_SIDE = "registry-side"


class Writer:
    def __init__(self, cn: str, own: tuple[str, ...] = ()):
        self.cn = cn  # CN template ("controller.{id}") or ADMIN/REGISTRY_SIDE
        self.own = set(own)  # dotted exprs that denote the writer's identity


# Module → the identity its TLS client cert carries when it writes.
WRITERS: dict[str, Writer] = {
    "oim_tpu/controller/controller.py": Writer(
        "controller.{id}", ("self.controller_id",)
    ),
    "oim_tpu/health/reporter.py": Writer(
        "controller.{id}", ("self.controller_id",)
    ),
    "oim_tpu/serve/registration.py": Writer("serve.{id}", ("self.serve_id",)),
    "oim_tpu/csi/rendezvous.py": Writer("host.{id}", ("host_id",)),
    # The event publisher's ``source`` IS its CommonName (events.py
    # docstring): controller.<id>, serve.<id>, ... each writing its own
    # events/<cn>/* subtree.  Its db-direct branch is the registry
    # process publishing its own events below the authz layer — the
    # key shape is identical, so it is checked the same way.
    "oim_tpu/common/events.py": Writer("{cn}", ("self.source",)),
    # The load publisher's ``cn`` IS its CommonName (serve.<id> for
    # oim-serve), writing exactly its own load/<cn> key — the events.py
    # shape applied to the autoscaler's observation plane.
    "oim_tpu/autoscale/load.py": Writer("{cn}", ("self.cn",)),
    # Operator CLI: authenticates as user.admin (grant "**").
    "oim_tpu/cli/oimctl.py": Writer(ADMIN),
    # The QoS policy publisher also runs as user.admin, but declares
    # the LITERAL CN instead of the ADMIN sentinel so the pass actually
    # resolves its qos/tenants write against the explicit grant row
    # (ADMIN-sentinel writers are skipped wholesale).
    "oim_tpu/qos/publish.py": Writer("user.admin"),
    # Fault-management runs registry-side, sharing the registry's DB:
    # its evictions/<vol> stores never cross the authz boundary.
    "oim_tpu/health/monitor.py": Writer(REGISTRY_SIDE),
    # The autoscaler shares the registry's DB the same way (embedded
    # beside it, or attached through the etcd stand-in replica plane):
    # its autoscale/replicas/* records and serve/<id>/address
    # withdrawals store below the authz boundary.
    "oim_tpu/autoscale/autoscaler.py": Writer(REGISTRY_SIDE),
}

# The registry package itself stores below the authz layer.
_SKIP_PREFIXES = ("oim_tpu/registry/", "oim_tpu/spec/")

_DB_RECEIVERS = {"db", "self.db", "self._db"}


def _load_grants():
    from oim_tpu.registry.authz import AUTHZ_GRANTS

    return AUTHZ_GRANTS


# -- path-expression resolution ---------------------------------------------


class _Resolver:
    """Resolve a path expression to one or more segment-pattern strings."""

    MAX_DEPTH = 6

    def __init__(self, tree: SourceTree, rel: str, own: set[str]):
        self.tree = tree
        self.rel = rel
        self.own = own

    def resolve(
        self, expr: ast.expr, fn: ast.FunctionDef | None, subst: dict
    ) -> list[str]:
        return self._expr(expr, fn, subst, self.rel, 0)

    def _expr(self, expr, fn, subst, rel, depth) -> list[str]:
        if depth > self.MAX_DEPTH:
            return [UNKNOWN]
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return [expr.value]
        name = dotted(expr)
        if name is not None:
            if name in self.own:
                return [OWN]
            if name in subst:
                sub_expr, sub_fn, sub_subst, sub_rel = subst[name]
                return self._expr(
                    sub_expr, sub_fn, sub_subst, sub_rel, depth + 1
                )
            resolved = self._name_binding(name, fn, rel)
            if resolved is not None:
                return self._expr(resolved, fn, subst, rel, depth + 1)
            return [STAR]
        if isinstance(expr, ast.JoinedStr):
            return self._joined(expr, fn, subst, rel, depth)
        if isinstance(expr, ast.IfExp):
            return self._expr(expr.body, fn, subst, rel, depth + 1) + self._expr(
                expr.orelse, fn, subst, rel, depth + 1
            )
        if isinstance(expr, ast.Call):
            return self._call(expr, fn, subst, rel, depth)
        return [STAR]

    def _joined(self, expr: ast.JoinedStr, fn, subst, rel, depth) -> list[str]:
        results = [""]
        for value in expr.values:
            if isinstance(value, ast.Constant):
                parts = [str(value.value)]
            elif isinstance(value, ast.FormattedValue):
                parts = self._expr(value.value, fn, subst, rel, depth + 1)
                # An interpolation that resolved stays; an unresolvable
                # one is one wildcard segment.
                parts = [STAR if p == UNKNOWN else p for p in parts]
            else:
                parts = [STAR]
            results = [r + p for r in results for p in parts]
        return results

    def _name_binding(self, name: str, fn, rel) -> ast.expr | None:
        """Nearest ``name = <str expr>`` binding: function-local first,
        then module-level constant."""
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        if isinstance(target, ast.Name) and target.id == name:
                            if isinstance(
                                node.value, (ast.Constant, ast.JoinedStr)
                            ):
                                return node.value
        mod = self.tree.tree(rel)
        if mod is not None:
            for node in mod.body:
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        if isinstance(target, ast.Name) and target.id == name:
                            if isinstance(node.value, ast.Constant):
                                return node.value
        return None

    def _call(self, expr: ast.Call, fn, subst, rel, depth) -> list[str]:
        callee = dotted(expr.func)
        if callee is None:
            return [STAR]
        target = self._find_function(callee, rel)
        if target is None:
            return [STAR]
        target_fn, target_rel = target
        new_subst = dict(subst)
        params = [a.arg for a in target_fn.args.args]
        for i, arg in enumerate(expr.args):
            if i < len(params):
                new_subst[params[i]] = (arg, fn, subst, rel)
        for kw in expr.keywords:
            if kw.arg:
                new_subst[kw.arg] = (kw.value, fn, subst, rel)
        # Unpassed defaulted params substitute their default value.
        defaults = target_fn.args.defaults
        if defaults:
            for param, default in zip(params[-len(defaults):], defaults):
                new_subst.setdefault(param, (default, target_fn, {}, target_rel))
        out: list[str] = []
        for node in ast.walk(target_fn):
            if isinstance(node, ast.Return) and node.value is not None:
                out.extend(
                    self._expr(node.value, target_fn, new_subst, target_rel, depth + 1)
                )
        return out or [UNKNOWN]

    def _find_function(self, callee: str, rel):
        """A module-local ``def`` or an imported ``module.func`` resolved
        through this file's oim_tpu imports."""
        parts = callee.split(".")
        mod = self.tree.tree(rel)
        if mod is None:
            return None
        if len(parts) == 1:
            for node in mod.body:
                if isinstance(node, ast.FunctionDef) and node.name == parts[0]:
                    return node, rel
            # from X import func
            for node in mod.body:
                if isinstance(node, ast.ImportFrom) and node.module:
                    for alias in node.names:
                        if (alias.asname or alias.name) == parts[0]:
                            target_rel = self._module_rel(node.module)
                            if target_rel is not None:
                                found = self._module_function(
                                    target_rel, alias.name
                                )
                                if found is not None:
                                    return found, target_rel
            # function-local imports (from X import func inside a def)
            for node in ast.walk(mod):
                if isinstance(node, ast.ImportFrom) and node.module:
                    for alias in node.names:
                        if (alias.asname or alias.name) == parts[0]:
                            target_rel = self._module_rel(node.module)
                            if target_rel is not None:
                                found = self._module_function(
                                    target_rel, alias.name
                                )
                                if found is not None:
                                    return found, target_rel
            return None
        if len(parts) == 2:
            mod_alias, func_name = parts
            for node in ast.walk(mod):
                if isinstance(node, ast.ImportFrom) and node.module:
                    for alias in node.names:
                        if (alias.asname or alias.name) == mod_alias:
                            target_rel = self._module_rel(
                                f"{node.module}.{alias.name}"
                            )
                            if target_rel is not None:
                                found = self._module_function(
                                    target_rel, func_name
                                )
                                if found is not None:
                                    return found, target_rel
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        if (alias.asname or alias.name) == mod_alias:
                            target_rel = self._module_rel(alias.name)
                            if target_rel is not None:
                                found = self._module_function(
                                    target_rel, func_name
                                )
                                if found is not None:
                                    return found, target_rel
        return None

    def _module_rel(self, module: str) -> str | None:
        rel = module.replace(".", "/") + ".py"
        try:
            self.tree.text(rel)
        except OSError:
            return None
        return rel

    def _module_function(self, rel: str, name: str) -> ast.FunctionDef | None:
        mod = self.tree.tree(rel)
        if mod is None:
            return None
        for node in mod.body:
            if isinstance(node, ast.FunctionDef) and node.name == name:
                return node
        return None


# -- write-site collection ---------------------------------------------------


def _enclosing_functions(mod: ast.Module):
    """(function, node) pairs mapping every node to its innermost def."""
    mapping: dict[int, ast.FunctionDef] = {}

    def visit(node, current):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            current = node
        mapping[id(node)] = current
        for child in ast.iter_child_nodes(node):
            visit(child, current)

    visit(mod, None)
    return mapping


def _write_sites(mod: ast.Module):
    """Yield (path_expr, call_node) for registry-write shapes."""
    for node in ast.walk(mod):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted(node.func) or ""
        short = callee.split(".")[-1]
        if short == "Value":
            for kw in node.keywords:
                if kw.arg == "path":
                    yield kw.value, node
        elif short == "store" and ".".join(callee.split(".")[:-1]) in _DB_RECEIVERS:
            if node.args:
                yield node.args[0], node


def _param_of(expr: ast.expr, fn: ast.FunctionDef | None) -> str | None:
    if fn is None or not isinstance(expr, ast.Name):
        return None
    params = {a.arg for a in fn.args.args}
    return expr.id if expr.id in params else None


def _call_sites(mod: ast.Module, func_name: str):
    for node in ast.walk(mod):
        if isinstance(node, ast.Call):
            callee = dotted(node.func)
            if callee == func_name or (
                callee and callee.split(".")[-1] == func_name
            ):
                yield node


# -- grant matching ----------------------------------------------------------


def _grant_covers(grants, writer_cn: str, pattern: str) -> bool:
    segs = pattern.split("/")
    for cn_pat, path_pat in grants:
        if path_pat == "**":
            if cn_pat == writer_cn:
                return True
            continue
        if cn_pat != "*" and cn_pat != writer_cn:
            continue
        psegs = path_pat.split("/")
        if len(psegs) != len(segs):
            continue
        ok = True
        for pat, seg in zip(psegs, segs):
            if pat == STAR:
                continue
            if pat == "{id}":
                if seg != OWN:
                    ok = False
                    break
            elif pat == "{cn}":
                # {cn} is the peer's FULL CommonName: it matches the
                # writer's own-identity hole only when the writer's CN
                # template IS the bare identity ("{cn}" writers).
                if not (seg == OWN and writer_cn == "{cn}"):
                    ok = False
                    break
            elif pat != seg:
                ok = False
                break
        if ok:
            return True
    return False


# -- the pass ----------------------------------------------------------------


def run(
    tree: SourceTree,
    grants=None,
    writers: dict[str, Writer] | None = None,
) -> list[Finding]:
    if grants is None:
        grants = _load_grants()
    if writers is None:
        writers = WRITERS
    findings: list[Finding] = []
    for rel in tree.files():
        if rel.startswith(_SKIP_PREFIXES):
            continue
        mod = tree.tree(rel)
        if mod is None:
            continue
        sites = list(_write_sites(mod))
        if not sites:
            continue
        writer = writers.get(rel)
        if writer is None:
            for _, call in sites:
                findings.append(
                    Finding(
                        PASS_ID,
                        rel,
                        call.lineno,
                        "registry write in a module with no WRITERS entry — "
                        "declare its identity in tools/oimlint/passes/"
                        "authz.py and grant it in oim_tpu/registry/authz.py",
                    )
                )
            continue
        if writer.cn in (ADMIN, REGISTRY_SIDE):
            continue
        enclosing = _enclosing_functions(mod)
        resolver = _Resolver(tree, rel, writer.own)
        for expr, call in sites:
            fn = enclosing.get(id(call))
            patterns = _resolve_site(resolver, mod, expr, fn, rel)
            for pattern in sorted(set(patterns)):
                if UNKNOWN in pattern.split("/") or pattern == UNKNOWN:
                    findings.append(
                        Finding(
                            PASS_ID,
                            rel,
                            call.lineno,
                            "unresolvable registry write path — refactor to "
                            "an f-string/key-helper the analyzer can read, "
                            "or waive with a justification",
                        )
                    )
                    continue
                if not _grant_covers(grants, writer.cn, pattern):
                    findings.append(
                        Finding(
                            PASS_ID,
                            rel,
                            call.lineno,
                            f"path pattern '{pattern}' written as "
                            f"{writer.cn} has no matching grant in "
                            "oim_tpu/registry/authz.py AUTHZ_GRANTS",
                        )
                    )
    return findings


def _resolve_site(resolver, mod, expr, fn, rel, depth: int = 0) -> list[str]:
    """Resolve a write site; a bare-parameter path is resolved at the
    enclosing function's intra-module call sites instead.  Depth-capped
    like the expression resolver: mutually recursive forwarders resolve
    to UNKNOWN (an 'unresolvable path' finding), never a RecursionError
    that would kill the whole lint run."""
    if depth > _Resolver.MAX_DEPTH:
        return [UNKNOWN]
    param = _param_of(expr, fn)
    if param is None:
        return resolver.resolve(expr, fn, {})
    index = [a.arg for a in fn.args.args].index(param)
    patterns: list[str] = []
    enclosing = _enclosing_functions(mod)
    for call in _call_sites(mod, fn.name):
        arg = None
        if index < len(call.args):
            arg = call.args[index]
        else:
            for kw in call.keywords:
                if kw.arg == param:
                    arg = kw.value
        if arg is None:
            continue
        caller_fn = enclosing.get(id(call))
        nested_param = _param_of(arg, caller_fn)
        if nested_param is not None and caller_fn is not fn:
            patterns.extend(
                _resolve_site(resolver, mod, arg, caller_fn, rel, depth + 1)
            )
        else:
            patterns.extend(resolver.resolve(arg, caller_fn, {}))
    return patterns or [UNKNOWN]
