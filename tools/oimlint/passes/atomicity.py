"""atomicity: check-then-act races on lock-guarded attributes.

The exact bug family ISSUE 6 fixed by hand in the serve plane's error
latch: ``self.error`` was mutated under ``self._error_lock`` everywhere
— except one path that READ it outside the lock to decide whether to
write it, so a stall-clear could clobber a driver-death error that
landed between its check and its store.  The lock-discipline pass
cannot see this (every *mutation* is properly guarded); the race is in
the unguarded *read that gates* the mutation.

Per class:

1. compute the guard map — for every instance attribute, the set of
   class locks held at its mutation sites (shared ``locksites``
   resolver: ``threading``-ctor and locksan-factory locks, ``with``
   nesting).  Mutations inside ``*_locked``-convention methods count
   as guarded (the caller holds the lock — which one is unknowable
   statically, recorded as a wildcard).  An attribute with at least
   one genuinely-guarded mutation OUTSIDE ``__init__`` is *guarded
   state*;
2. flag every ``if`` whose test reads a guarded attribute with none of
   that attribute's guard locks held, when the gated suite mutates the
   same attribute or a sibling (one sharing a guard lock).  Moving the
   check under the lock is always the fix — the finding names the
   attribute, the gating read, the mutated sibling, and the lock.

Methods named ``*_locked`` are exempt as checkers (their caller holds
the lock by convention), as are constructors (single-threaded by
contract).  A deliberate lock-free fast path carries an in-code
``# oimlint: disable=atomicity`` waiver with a justification, same as
every other pass.
"""

from __future__ import annotations

import ast

from tools.oimlint.core import Finding, SourceTree, class_methods, module_classes
from tools.oimlint.passes import locksites
from tools.oimlint.passes.locksites import HeldLockWalker, LockNode, self_reads

PASS_ID = "atomicity"
DESCRIPTION = "guarded attrs must not be read lock-free to gate mutations"

_LIFECYCLE_SKIP = {"__init__", "__new__", "__post_init__"}

# Wildcard guard for mutations inside *_locked-convention methods.
_CONVENTION = "<caller-held>"


class _GuardScan(HeldLockWalker):
    """Mutation sites with the lock set held at each."""

    def __init__(self, cls_name, own_locks, index):
        super().__init__(cls_name, own_locks, index)
        # attr -> list[(line, frozenset[lock names held])]
        self.mutations: dict[str, list[tuple[int, frozenset]]] = {}

    def on_mutate(self, attr: str, line: int) -> None:
        held = frozenset(
            h.name for h in self.held if h.owner == self.cls_name
        )
        self.mutations.setdefault(attr, []).append((line, held))


class _CheckScan(HeldLockWalker):
    """``if`` tests reading guarded attrs, with held state and the
    mutations inside each gated suite."""

    def __init__(self, cls_name, own_locks, index, guards):
        super().__init__(cls_name, own_locks, index)
        self.guards = guards  # attr -> frozenset of guard lock names
        # (line, read_attr, mutated_attr, mut_line)
        self.races: list[tuple[int, str, str, int]] = []

    def on_test(self, test: ast.expr, line: int, body: list[ast.stmt]) -> None:
        reads = {
            attr: rline
            for attr, rline in self_reads(test).items()
            if attr in self.guards
        }
        if not reads:
            return
        held = {h.name for h in self.held if h.owner == self.cls_name}
        unguarded = {
            attr: rline
            for attr, rline in reads.items()
            if not (held & self.guards[attr])
            and not (_CONVENTION in self.guards[attr] and held)
        }
        if not unguarded:
            return
        muts = _suite_mutations(body, self.cls_name, self.own_locks, self.index)
        for attr, rline in sorted(unguarded.items()):
            for mut_attr, mut_line in sorted(muts.items()):
                if mut_attr not in self.guards:
                    continue
                shared = self.guards[attr] & self.guards[mut_attr]
                related = (
                    mut_attr == attr
                    or (shared - {_CONVENTION})
                    or (_CONVENTION in self.guards[attr])
                    or (_CONVENTION in self.guards[mut_attr])
                )
                if related:
                    self.races.append((line, attr, mut_attr, mut_line))
                    break  # one finding per gating read


def _suite_mutations(
    body: list[ast.stmt], cls_name, own_locks, index
) -> dict[str, int]:
    """Attrs mutated anywhere in the gated suite (locked or not — the
    race is the check outside, wherever the act runs)."""

    class _Muts(HeldLockWalker):
        def __init__(self):
            super().__init__(cls_name, own_locks, index)
            self.out: dict[str, int] = {}

        def on_mutate(self, attr: str, line: int) -> None:
            self.out.setdefault(attr, line)

    scan = _Muts()
    for stmt in body:
        scan.visit(stmt)
    return scan.out


def _class_findings(rel: str, cls: ast.ClassDef, index) -> list[Finding]:
    own_locks = locksites.class_lock_attrs(cls)
    if not own_locks:
        return []
    methods = class_methods(cls)

    # Phase 1: the guard map.
    guards: dict[str, set[str]] = {}
    unguarded_elsewhere: set[str] = set()
    for name, fn in methods.items():
        scan = _GuardScan(cls.name, own_locks, index)
        for stmt in fn.body:
            scan.visit(stmt)
        convention = name.endswith("_locked")
        for attr, sites in scan.mutations.items():
            for line, held in sites:
                if name in _LIFECYCLE_SKIP:
                    continue  # constructor writes are pre-publication
                if held:
                    guards.setdefault(attr, set()).update(held)
                elif convention:
                    guards.setdefault(attr, set()).add(_CONVENTION)
                else:
                    unguarded_elsewhere.add(attr)
        del scan

    # Guarded state = attrs with at least one guarded mutation.  Attrs
    # ONLY ever guarded by convention with no concrete lock anywhere
    # stay in (the *_locked body is the guarded half).
    guard_map = {attr: frozenset(locks) for attr, locks in guards.items()}
    if not guard_map:
        return []

    # Phase 2: unguarded gating reads.
    findings: list[Finding] = []
    for name, fn in methods.items():
        if name in _LIFECYCLE_SKIP or name.endswith("_locked"):
            continue
        scan = _CheckScan(cls.name, own_locks, index, guard_map)
        for stmt in fn.body:
            scan.visit(stmt)
        for line, attr, mut_attr, _mut_line in scan.races:
            locks = sorted(guard_map[attr] - {_CONVENTION}) or sorted(
                guard_map[mut_attr] - {_CONVENTION}
            )
            lock_desc = "/".join(locks) if locks else "the caller-held lock"
            act = (
                f"a mutation of self.{mut_attr}"
                if mut_attr != attr
                else f"its own mutation"
            )
            findings.append(
                Finding(
                    PASS_ID,
                    rel,
                    line,
                    f"{cls.name}.{name}: check-then-act race: self.{attr} "
                    f"(guarded by {lock_desc}) is read without the lock to "
                    f"gate {act}; move the check under the lock",
                )
            )
    return findings


def run(tree: SourceTree) -> list[Finding]:
    index = locksites.lock_index(tree)
    findings: list[Finding] = []
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        for cls in module_classes(mod):
            findings.extend(_class_findings(rel, cls, index))
    return findings
