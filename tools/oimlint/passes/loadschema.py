"""load-schema-drift: the ``load/<cn>`` snapshot schema's three surfaces.

PR 17 and PR 18 each added fields to the serving-load snapshot
(``autoscale/load.py`` ``_DEFAULTS``), and each had to remember by hand
that the schema has three surfaces: the publisher/decoder field set,
the load-field table in ``doc/serving.md``, and the ``oimctl top``/
``oimctl kv`` column accessors.  This pass pins them together the
protocol-drift way, so the next schema addition cannot silently skip a
surface:

- **published**: the literal keys of the ``_DEFAULTS`` dict in
  ``oim_tpu/autoscale/load.py`` — the tolerant-decode contract every
  consumer indexes unconditionally;
- **documented**: the field rows of the ``| field | meaning |`` table
  in ``doc/serving.md`` (compound rows like ```active_slots` /
  `total_slots` `` document several fields at once);
- **rendered**: every ``load.get("...")`` key in
  ``oim_tpu/cli/oimctl.py`` (the convention: the decoded snapshot is
  always bound to a variable named ``load`` in the render helpers).

Drift rules: published ↔ documented must agree BOTH ways (every field
documented, no phantom doc rows); every rendered key must exist in
``_DEFAULTS`` (a stale accessor renders a permanent default and reads
as "nothing happening").  The reverse — a published field with no
oimctl column — is legal: not every field is a column (``ts`` is a
staleness input, ``tenants`` renders through ``oimctl tenants``'s
fleet-merged view instead).
"""

from __future__ import annotations

import ast
import re

from tools.oimlint.core import Finding, SourceTree

PASS_ID = "load-schema-drift"
DESCRIPTION = "load.py _DEFAULTS / doc load-field table / oimctl accessors agree"

LOAD_FILE = "oim_tpu/autoscale/load.py"
CLI_FILE = "oim_tpu/cli/oimctl.py"
DOC_FILE = "doc/serving.md"

_TABLE_HEADER = re.compile(r"^\|\s*field\s*\|\s*meaning\s*\|$")
_FIELD_RE = re.compile(r"`([a-z][a-z0-9_]*)`")


def _tree_or_none(tree: SourceTree, rel: str):
    try:
        return tree.tree(rel)
    except OSError:
        return None


def published_fields(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    """The literal keys of the ``_DEFAULTS`` dict assignment."""
    out: dict[str, tuple[str, int]] = {}
    mod = _tree_or_none(tree, rel)
    if mod is None:
        return out
    for node in ast.walk(mod):
        # Both spellings: `_DEFAULTS = {...}` and the annotated
        # `_DEFAULTS: dict[str, Any] = {...}` load.py actually uses.
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.value, ast.Dict)
            and isinstance(node.target, ast.Name)
        ):
            targets = [node.target.id]
        else:
            continue
        if "_DEFAULTS" not in targets:
            continue
        for key in node.value.keys:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                out.setdefault(key.value, (rel, key.lineno))
    return out


def documented_fields(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    """Field names from the first column of the ``| field | meaning |``
    table (only that table — the doc has other tables)."""
    out: dict[str, tuple[str, int]] = {}
    try:
        lines = tree.lines(rel)
    except OSError:
        return out
    in_table = False
    for lineno, line in enumerate(lines, 1):
        stripped = line.strip()
        if _TABLE_HEADER.match(stripped):
            in_table = True
            continue
        if not in_table:
            continue
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = stripped.split("|")
        if len(cells) < 2 or set(cells[1].strip()) <= {"-", " "}:
            continue  # the |---|---| separator row
        for name in _FIELD_RE.findall(cells[1]):
            out.setdefault(name, (rel, lineno))
    return out


def rendered_fields(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    """Keys of every ``load.get("...")`` call — the render-helper
    convention for the decoded snapshot."""
    out: dict[str, tuple[str, int]] = {}
    mod = _tree_or_none(tree, rel)
    if mod is None:
        return out
    for node in ast.walk(mod):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "load"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        out.setdefault(node.args[0].value, (rel, node.lineno))
    return out


def run(
    tree: SourceTree,
    load_file: str = LOAD_FILE,
    cli_file: str = CLI_FILE,
    doc_file: str = DOC_FILE,
) -> list[Finding]:
    published = published_fields(tree, load_file)
    documented = documented_fields(tree, doc_file)
    rendered = rendered_fields(tree, cli_file)
    findings: list[Finding] = []
    if not published:
        return findings  # fixture run without the load module

    for name in sorted(set(published) - set(documented)):
        rel, line = published[name]
        findings.append(
            Finding(
                PASS_ID, rel, line,
                f"load field {name!r} is published in _DEFAULTS but missing "
                f"from the {doc_file} load-field table",
            )
        )
    for name in sorted(set(documented) - set(published)):
        rel, line = documented[name]
        findings.append(
            Finding(
                PASS_ID, rel, line,
                f"load field {name!r} is documented but absent from "
                f"{load_file} _DEFAULTS (phantom row)",
            )
        )
    for name in sorted(set(rendered) - set(published)):
        rel, line = rendered[name]
        findings.append(
            Finding(
                PASS_ID, rel, line,
                f"oimctl renders load field {name!r} which is absent from "
                f"{load_file} _DEFAULTS (stale accessor renders a default "
                f"forever)",
            )
        )
    return findings
