"""oimvet pass registry.  A pass module exports ``PASS_ID``,
``DESCRIPTION`` and ``run(tree: SourceTree) -> list[Finding]``; adding a
pass = adding a module here and one line to ``ALL_PASSES`` (see
doc/development.md "The oimvet static analyzer")."""

from __future__ import annotations

from tools.oimlint.passes import (
    authz,
    deadline,
    lifecycle,
    lockdiscipline,
    metricspass,
    protocol,
)

ALL_PASSES = {
    mod.PASS_ID: mod
    for mod in (
        lockdiscipline,
        lifecycle,
        authz,
        protocol,
        deadline,
        metricspass,
    )
}
