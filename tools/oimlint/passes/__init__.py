"""oimvet pass registry.  A pass module exports ``PASS_ID``,
``DESCRIPTION`` and ``run(tree: SourceTree) -> list[Finding]``; adding a
pass = adding a module here and one line to ``ALL_PASSES`` (see
doc/development.md "The oimvet static analyzer")."""

from __future__ import annotations

from tools.oimlint.passes import (
    atomicity,
    authz,
    deadline,
    donation,
    hostsync,
    lifecycle,
    lockdiscipline,
    lockorder,
    loadschema,
    metricspass,
    protocol,
    retrace,
)

ALL_PASSES = {
    mod.PASS_ID: mod
    for mod in (
        lockdiscipline,
        lockorder,
        atomicity,
        lifecycle,
        authz,
        protocol,
        loadschema,
        deadline,
        metricspass,
        donation,
        hostsync,
        retrace,
    )
}

# The jaxvet family (ISSUE 11): the three JAX hot-path hygiene passes,
# runnable standalone via `make lint-jax` / `--passes` with this list.
JAX_PASSES = (donation.PASS_ID, hostsync.PASS_ID, retrace.PASS_ID)

# The concvet family (ISSUE 19): the two concurrency passes, runnable
# standalone via `make lint-conc` / `--passes` with this list (their
# runtime complement is oim_tpu/common/locksan.py, OIM_LOCK_SANITIZER=1).
CONC_PASSES = (lockorder.PASS_ID, atomicity.PASS_ID)
