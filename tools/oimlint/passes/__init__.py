"""oimvet pass registry.  A pass module exports ``PASS_ID``,
``DESCRIPTION`` and ``run(tree: SourceTree) -> list[Finding]``; adding a
pass = adding a module here and one line to ``ALL_PASSES`` (see
doc/development.md "The oimvet static analyzer")."""

from __future__ import annotations

from tools.oimlint.passes import (
    authz,
    deadline,
    donation,
    hostsync,
    lifecycle,
    lockdiscipline,
    metricspass,
    protocol,
    retrace,
)

ALL_PASSES = {
    mod.PASS_ID: mod
    for mod in (
        lockdiscipline,
        lifecycle,
        authz,
        protocol,
        deadline,
        metricspass,
        donation,
        hostsync,
        retrace,
    )
}

# The jaxvet family (ISSUE 11): the three JAX hot-path hygiene passes,
# runnable standalone via `make lint-jax` / `--passes` with this list.
JAX_PASSES = (donation.PASS_ID, hostsync.PASS_ID, retrace.PASS_ID)
