"""Shared lock-site resolver for the concvet pass family.

The two concurrency passes (``lock-order``, ``atomicity``) need the same
map the jaxvet family gets from ``jaxsites``: which instance/class
attributes of every class are locks, and — per method — what happens
while each lock is held.  This module builds that map once per tree,
handling the lock-construction shapes this codebase actually uses:

- direct:     ``self._lock = threading.Lock()`` (also ``RLock``,
  ``Condition``) in any method, or a ClassDef-level
  ``_instance_lock = threading.Lock()``;
- sanitized:  ``self._lock = locksan.new_lock("Engine._lock")`` — the
  runtime lock-order sanitizer's factory spellings
  (``oim_tpu/common/locksan.py``) construct the same lock objects and
  count identically, so adopting the sanitizer never blinds the
  analyzer;
- composed:   ``with self._host.lock:`` / ``with other._ring_lock:`` —
  a lock attribute reached through another object.  Resolution is by
  attribute NAME across the whole-tree lock index: a name owned by
  exactly one class resolves to that class's lock node; an ambiguous
  name (``_lock`` is owned by a dozen classes) is skipped, never
  guessed (the jaxsites over/under-approximation contract — silence
  beats a wrong edge, and the runtime sanitizer covers what static
  name resolution cannot).

Lock nodes are ``ClassName.attr`` strings; the node also remembers the
constructor kind (``Lock``/``RLock``/``Condition``) so the lock-order
pass can tell a re-entrant acquisition from a self-deadlock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.oimlint.core import SourceTree, call_name, dotted, module_classes

# Constructor spellings that produce a lock: the threading ctors plus
# the locksan sanitizer factories (which return the same objects, or an
# order-checking wrapper, depending on OIM_LOCK_SANITIZER).
LOCK_CTOR_KINDS = {
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
    "new_lock": "Lock",
    "new_rlock": "RLock",
    "new_condition": "Condition",
}

_LIFECYCLE = {"__init__", "__new__", "__post_init__"}


@dataclass(frozen=True)
class LockNode:
    """One resolved lock: ``owner`` class name, ``attr`` name, ctor kind."""

    owner: str
    attr: str
    kind: str = "Lock"

    @property
    def name(self) -> str:
        return f"{self.owner}.{self.attr}"


@dataclass
class ClassLockInfo:
    """Lock attributes of one class: attr name → ctor kind."""

    cls_name: str
    rel: str
    locks: dict[str, str] = field(default_factory=dict)


def class_lock_attrs(cls: ast.ClassDef) -> dict[str, str]:
    """``{attr: kind}`` for every lock the class constructs, whether
    ``self.X = ...`` inside a method or ``X = ...`` at class level."""
    locks: dict[str, str] = {}
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        ctor = (call_name(node.value) or "").split(".")[-1]
        if ctor not in LOCK_CTOR_KINDS:
            continue
        for target in node.targets:
            t = dotted(target)
            if t and t.startswith("self.") and t.count(".") == 1:
                locks[t.split(".", 1)[1]] = LOCK_CTOR_KINDS[ctor]
            elif isinstance(target, ast.Name):
                # ClassDef-level lock (Engine._instance_lock) — but only
                # when the assignment is a direct child of the class
                # body, not a local inside a method.
                if any(node is stmt for stmt in cls.body):
                    locks[target.id] = LOCK_CTOR_KINDS[ctor]
    return locks


def lock_index(tree: SourceTree) -> dict[str, list[LockNode]]:
    """Whole-tree index: lock attribute name → every class that owns
    one (the composition-resolution table).  Memoized on the tree
    instance like the jaxsites factory index."""
    cached = getattr(tree, "_locksites_index", None)
    if cached is not None:
        return cached
    out: dict[str, list[LockNode]] = {}
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        for cls in module_classes(mod):
            for attr, kind in class_lock_attrs(cls).items():
                out.setdefault(attr, []).append(LockNode(cls.name, attr, kind))
    tree._locksites_index = out  # type: ignore[attr-defined]
    return out


def resolve_lock_expr(
    expr: ast.expr,
    cls_name: str,
    own_locks: dict[str, str],
    index: dict[str, list[LockNode]],
) -> LockNode | None:
    """The lock node a ``with``-item acquires, or None.

    ``with self.X:`` resolves against the class's own lock attrs first;
    any other dotted chain ending in a known lock attr resolves through
    the whole-tree index when the attr name is owned by exactly one
    class (unique-name composition, the documented approximation)."""
    name = dotted(expr)
    if name is None and isinstance(expr, ast.Call):
        name = dotted(expr.func)  # with self._lock.acquire_timeout()
    if not name or "." not in name:
        return None
    head, attr = name.rsplit(".", 1)
    if head == "self" and attr in own_locks:
        return LockNode(cls_name, attr, own_locks[attr])
    owners = index.get(attr, [])
    if head == cls_name:
        # Explicit class-qualified access (Engine._instance_lock).
        for node in owners:
            if node.owner == cls_name:
                return node
    if len(owners) == 1:
        return owners[0]
    return None  # unknown or ambiguous: skipped, never guessed


class HeldLockWalker(ast.NodeVisitor):
    """Method-body walk with a held-lock stack, for subclass hooks.

    Tracks ``with``-acquisitions of resolvable lock nodes (nested
    classes are fenced off — different ``self``; nested defs/lambdas
    close over the outer ``self`` and are descended into, matching the
    lock-discipline pass).  Subclasses override the ``on_*`` hooks."""

    def __init__(
        self,
        cls_name: str,
        own_locks: dict[str, str],
        index: dict[str, list[LockNode]],
    ):
        self.cls_name = cls_name
        self.own_locks = own_locks
        self.index = index
        self.held: list[LockNode] = []

    # -- hooks -------------------------------------------------------------

    def on_acquire(self, node: LockNode, line: int) -> None:
        """Called when a ``with``-item acquires ``node`` (held stack
        reflects the state BEFORE the acquisition)."""

    def on_self_call(self, method: str, line: int) -> None:
        """Called for every ``self.m(...)`` call."""

    def on_mutate(self, attr: str, line: int) -> None:
        """Called for every mutation of ``self.attr``."""

    def on_test(self, test: ast.expr, line: int, body: list[ast.stmt]) -> None:
        """Called for every ``if`` test (held stack = state at the
        check); ``body`` is the gated suite (body + orelse)."""

    # -- scope fencing -----------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass  # nested class: different ``self``

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # Nested defs close over self but run at an unknowable time
        # with an unknowable held set — walk them with an EMPTY held
        # stack (callbacks fire on other threads; assuming the
        # enclosing locks are held would fabricate edges).
        saved, self.held = self.held, []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- lock tracking -----------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        entered: list[LockNode] = []
        for item in node.items:
            resolved = resolve_lock_expr(
                item.context_expr, self.cls_name, self.own_locks, self.index
            )
            self.visit(item.context_expr)
            if resolved is not None:
                self.on_acquire(resolved, item.context_expr.lineno)
                self.held.append(resolved)
                entered.append(resolved)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(entered):]

    # -- calls and mutations -----------------------------------------------

    _MUTATORS = {
        "append", "appendleft", "add", "insert", "extend", "update", "pop",
        "popleft", "popitem", "clear", "remove", "discard", "setdefault",
    }

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func) or ""
        parts = name.split(".")
        if len(parts) == 2 and parts[0] == "self":
            self.on_self_call(parts[1], node.lineno)
        if (
            len(parts) == 3
            and parts[0] == "self"
            and parts[2] in self._MUTATORS
            and parts[1] not in self.own_locks
        ):
            self.on_mutate(parts[1], node.lineno)
        self.generic_visit(node)

    def _mutate_target(self, target: ast.AST, line: int) -> None:
        if isinstance(target, ast.Subscript):
            self._mutate_target(target.value, line)  # self.X[k] = v
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._mutate_target(elt, line)
            return
        name = dotted(target)
        if name and name.startswith("self.") and name.count(".") == 1:
            attr = name.split(".", 1)[1]
            if attr not in self.own_locks:
                self.on_mutate(attr, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._mutate_target(target, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mutate_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._mutate_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._mutate_target(target, node.lineno)

    def visit_If(self, node: ast.If) -> None:
        self.on_test(node.test, node.lineno, list(node.body) + list(node.orelse))
        self.visit(node.test)
        for stmt in node.body:
            self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)


def self_reads(expr: ast.expr) -> dict[str, int]:
    """``{attr: line}`` for every ``self.X`` READ inside ``expr``
    (attribute loads, including through subscripts/calls on the
    attribute — ``self._events[rid]``, ``self._profile.get(...)``,
    ``rid in self._errors``)."""
    out: dict[str, int] = {}
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and isinstance(node.ctx, ast.Load)
        ):
            out.setdefault(node.attr, node.lineno)
    return out
