"""retrace-risk: jit signatures that recompile under live traffic.

An XLA compile is 20-40 s on a TPU; the serve engine's contract is that
``warmup()`` pays every compile before traffic lands and steady state
pays zero.  Three statically-visible ways code breaks that contract:

1. **python branch on a traced parameter** — inside a jit-wrapped
   function body, ``if``/``while`` on a parameter that is not static
   (``static_argnums``/``static_argnames``, a ``partial(...)``-bound
   keyword, or a keyword-only config param — the tree's idiom for
   trace-time constants).  Passed an array it raises at trace time;
   passed a Python scalar it silently compiles one variant per value.
   ``isinstance(...)`` dispatch, ``is None`` checks, and
   ``.shape/.ndim/.dtype`` reads are trace-stable and exempt.
2. **varying python scalar at a traced position** — a call site of a
   jitted binding feeding ``len(...)`` (or a local assigned from
   ``len(...)``) at a non-static position: the scalar is hashed into
   the jit cache key by value, so every distinct length is a fresh
   compile.  Wrap it (``np.int32(...)``/``jnp.asarray``) or make the
   position static.
3. **jit constructed per iteration** — ``jax.jit(...)`` inside a
   ``for``/``while`` body or inside a hot-path function: each
   construction starts a brand-new trace cache, so the "cached" compile
   is paid every step.  Build-once tables (dict comprehensions in
   ``__init__``) are exempt.  ``pl.pallas_call(...)`` in a loop is the
   same failure shape (every construction is a fresh wrapped kernel)
   and is flagged too — but NOT in hot-path functions, because the
   kernel-wrapper idiom (``ops/paged_attention.py``) constructs the
   call inside a function that only runs under an enclosing jit, where
   construction is trace-time and the outer program caches it.

The static passes cannot see every retrace (shape-dependent
recompiles, weak-type promotion); the runtime complement is the
steady-state recompile guard (``tests/test_jit_guard.py``, ``make
test-jit-guard``), which counts XLA compiles around a warm engine.
"""

from __future__ import annotations

import ast

from tools.oimlint.core import Finding, SourceTree, dotted
from tools.oimlint.passes import jaxsites

PASS_ID = "retrace-risk"
DESCRIPTION = "jit bodies/call sites must not recompile at steady state"

_TRACE_STABLE_ATTRS = {"shape", "ndim", "dtype", "size"}


def _find_function(mod: ast.Module, name: str) -> ast.FunctionDef | None:
    for node in ast.walk(mod):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == name
        ):
            return node  # type: ignore[return-value]
    return None


def _traced_params(fn: ast.FunctionDef, site: jaxsites.JitSite) -> set[str]:
    """Positional parameter names that are traced (not static) under
    ``site``.  Keyword-only params are the tree's config idiom and are
    treated as static, as are partial-bound keywords and
    static_argnums/argnames."""
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static = {
        pos[i] for i in site.static if i < len(pos)
    } | set(site.static_names) | set(site.bound_kwargs)
    return {p for p in pos if p not in static}


def _branch_params(test: ast.expr, traced: set[str]) -> set[str]:
    """Traced params a branch test's outcome depends on, minus the
    trace-stable readings (isinstance dispatch, ``is None``,
    ``.shape``-family attributes)."""
    out: set[str] = set()

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            callee = dotted(node.func) or ""
            if callee.split(".")[-1] == "isinstance":
                return  # type dispatch is trace-static
            if isinstance(node.func, ast.Attribute):
                walk(node.func.value)
            for arg in node.args:
                walk(arg)
            return
        if isinstance(node, ast.Attribute):
            if node.attr in _TRACE_STABLE_ATTRS:
                return
            walk(node.value)
            return
        if isinstance(node, ast.Compare):
            ops_are_identity = all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            )
            if ops_are_identity:
                return  # ``x is None`` — a type-level, trace-static test
        if isinstance(node, ast.Name) and node.id in traced:
            out.add(node.id)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(test)
    return out


def _check_jit_body(
    rel: str, mod: ast.Module, site: jaxsites.JitSite
) -> list[Finding]:
    fn = _find_function(mod, site.target or "")
    if fn is None:
        return []
    traced = _traced_params(fn, site)
    findings: list[Finding] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            for param in sorted(_branch_params(node.test, traced)):
                findings.append(Finding(
                    PASS_ID, rel, node.lineno,
                    f"jit-wrapped {fn.name}: python-level branch on "
                    f"traced parameter '{param}' — an array raises at "
                    "trace time, a python scalar compiles one variant "
                    "per value (use lax.cond/jnp.where, or make it "
                    "static)",
                ))
    return findings


def _len_locals(fn: ast.AST) -> set[str]:
    """Locals assigned directly from ``len(...)``."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and (dotted(node.value.func) or "") == "len"
        ):
            for target in node.targets:
                name = dotted(target)
                if name:
                    out.add(name)
    return out


def _is_len_expr(node: ast.expr, len_locals: set[str]) -> bool:
    if isinstance(node, ast.Call) and (dotted(node.func) or "") == "len":
        return True
    if isinstance(node, ast.Name) and node.id in len_locals:
        return True
    if isinstance(node, ast.BinOp):
        return _is_len_expr(node.left, len_locals) or _is_len_expr(
            node.right, len_locals
        )
    return False


def _check_call_sites(
    rel: str, mod: ast.Module,
    bindings: dict[str, list[jaxsites.JitSite]],
) -> list[Finding]:
    findings: list[Finding] = []
    for fn in ast.walk(mod):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        len_locals = _len_locals(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            variants = bindings.get(dotted(node.func) or "")
            if not variants:
                continue
            matched = jaxsites.sites_for_call(variants, len(node.args))
            static = {
                pos for site in matched for pos in site.static
            }
            binding = matched[0].binding
            for pos, arg in enumerate(node.args):
                if pos in static:
                    continue
                if _is_len_expr(arg, len_locals):
                    findings.append(Finding(
                        PASS_ID, rel, arg.lineno,
                        f"{binding}(...): python scalar from len() "
                        f"at traced position {pos} — every distinct "
                        "value is a fresh compile (wrap in "
                        "np.int32/jnp.asarray, or mark the position "
                        "static)",
                    ))
    return findings


def _check_jit_in_loops(
    tree: SourceTree, rel: str, mod: ast.Module,
    table: dict[str, tuple[str, ...]] | None,
) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[int] = set()  # a jit under nested loops flags ONCE
    for node in ast.walk(mod):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            for child in ast.walk(node):
                if child is not node and id(child) not in seen:
                    if jaxsites.is_jit_call(child):
                        seen.add(id(child))
                        findings.append(Finding(
                            PASS_ID, rel, child.lineno,
                            "jax.jit(...) constructed inside a loop — "
                            "each construction is a fresh trace cache, "
                            "so the compile is paid every iteration "
                            "(hoist it)",
                        ))
                    elif jaxsites.is_pallas_call(child):
                        # Same failure shape as jit-in-loop: every
                        # pallas_call(...) is a new wrapped kernel with
                        # its own trace cache.  NOT flagged in hot-path
                        # functions below: the kernel-wrapper idiom
                        # (flash_attention/paged_attention) constructs
                        # the call inside a function that only ever
                        # runs under an enclosing jit trace, where
                        # construction is trace-time and cached by the
                        # outer program.
                        seen.add(id(child))
                        findings.append(Finding(
                            PASS_ID, rel, child.lineno,
                            "pl.pallas_call(...) constructed inside a "
                            "loop — each construction re-lowers the "
                            "kernel, so the compile is paid every "
                            "iteration (hoist it, or wrap the call in "
                            "a jitted function)",
                        ))
    hot = jaxsites.hotpath_functions(tree, rel, table)
    flagged = {f.line for f in findings}
    for name, fn in hot.items():
        for child in ast.walk(fn):
            if jaxsites.is_jit_call(child) and child.lineno not in flagged:
                findings.append(Finding(
                    PASS_ID, rel, child.lineno,
                    f"{name}: jax.jit(...) constructed inside a hot-path "
                    "function — the per-call construction discards the "
                    "trace cache (hoist it to __init__)",
                ))
    return findings


def run(
    tree: SourceTree,
    table: dict[str, tuple[str, ...]] | None = None,
) -> list[Finding]:
    findings: list[Finding] = []
    factories = jaxsites.tree_factories(tree)
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        sites = jaxsites.resolve(tree, rel, factories)
        # Dedupe bodies per STATIC SIGNATURE, not per target name: the
        # same function wrapped twice (once with static_argnums, once
        # without) traces differently, and only the unstatic wrapping
        # may branch-retrace.  Findings dedupe by line so the common
        # case (identical re-wrappings) still reports once.
        seen: set[tuple] = set()
        body_findings: dict[tuple[int, str], Finding] = {}
        for site in sites.all_sites:
            key = (
                site.target, site.static, site.static_names,
                site.bound_kwargs,
            )
            if site.target and key not in seen:
                seen.add(key)
                for f in _check_jit_body(rel, mod, site):
                    body_findings.setdefault((f.line, f.message), f)
        findings.extend(body_findings.values())
        findings.extend(_check_call_sites(rel, mod, sites.by_binding))
        findings.extend(_check_jit_in_loops(tree, rel, mod, table))
    return findings
