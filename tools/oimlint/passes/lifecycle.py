"""resource-lifecycle: threads/sockets/channels need a teardown path.

A class that stores a ``threading.Thread``, a raw socket, or a grpc
channel on ``self`` owns that resource for its whole lifetime, so:

1. it must define a teardown method (``close``/``stop``/``shutdown``/
   ``__exit__``) — a daemon that cannot be shut down cleanly cannot be
   embedded, restarted in-process, or soak-tested without leaking;
2. every ``self``-stored thread must be ``join()``-ed somewhere in the
   class (directly or through a local alias) — an unjoined loop thread
   keeps running against torn-down state after ``stop()`` returns,
   which is exactly how "stopped" routers kept probing dead backends;
   non-daemon threads additionally block interpreter exit;
3. every ``self``-stored socket/channel must at least be *touched* by
   the teardown path (loaded somewhere reachable from it), the weakest
   check that still catches a close() that plain forgot the resource.

Aliases are followed one level (``t = self._thread; t.join()`` and the
tuple form ``a, b = self._x, self._y`` both count).
"""

from __future__ import annotations

import ast

from tools.oimlint.core import (
    Finding,
    SourceTree,
    call_name,
    class_methods,
    dotted,
    keyword_arg,
    module_classes,
)

PASS_ID = "resource-lifecycle"
DESCRIPTION = "thread/socket/channel owners need close(); threads joined"

_TEARDOWN = ("close", "stop", "shutdown", "__exit__", "__del__")

_RESOURCE_CTORS = {
    "Thread": "thread",
    "socket": "socket",
    "secure_channel": "grpc channel",
    "insecure_channel": "grpc channel",
}


def _self_attr(target: ast.AST) -> str | None:
    name = dotted(target)
    if name and name.startswith("self.") and name.count(".") == 1:
        return name.split(".", 1)[1]
    return None


def _resource_kind(value: ast.AST) -> tuple[str, bool] | None:
    """(kind, daemon) when ``value`` contains a resource constructor call
    anywhere (covers ``x if cond else None`` wrappers)."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            name = (call_name(node) or "").split(".")[-1]
            kind = _RESOURCE_CTORS.get(name)
            if kind is None:
                continue
            daemon = False
            if kind == "thread":
                arg = keyword_arg(node, "daemon")
                daemon = isinstance(arg, ast.Constant) and arg.value is True
            return kind, daemon
    return None


def _collect_resources(cls: ast.ClassDef) -> dict[str, tuple[str, bool, int]]:
    """attr -> (kind, daemon, line).  Tracks one level of local aliasing
    (``sock = socket.socket(); ...; self._sock = sock``)."""
    resources: dict[str, tuple[str, bool, int]] = {}
    for fn in class_methods(cls).values():
        local_kinds: dict[str, tuple[str, bool]] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            kind = _resource_kind(node.value)
            for target in node.targets:
                attr = _self_attr(target)
                if kind is not None:
                    if attr is not None:
                        resources.setdefault(
                            attr, (kind[0], kind[1], node.lineno)
                        )
                    elif isinstance(target, ast.Name):
                        local_kinds[target.id] = kind
                elif attr is not None and isinstance(node.value, ast.Name):
                    aliased = local_kinds.get(node.value.id)
                    if aliased is not None:
                        resources.setdefault(
                            attr, (aliased[0], aliased[1], node.lineno)
                        )
    return resources


def _alias_map(fn: ast.FunctionDef) -> dict[str, set[str]]:
    """local name -> self attrs it may alias: ``t = self._x``, tuple
    unpacks, and ``for t in (self._x, self._y):`` loops."""
    aliases: dict[str, set[str]] = {}

    def alias(name: str, value: ast.AST) -> None:
        attr = _self_attr(value)
        if attr is not None:
            aliases.setdefault(name, set()).add(attr)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    alias(target.id, node.value)
                elif isinstance(target, (ast.Tuple, ast.List)) and isinstance(
                    node.value, (ast.Tuple, ast.List)
                ):
                    for t, v in zip(target.elts, node.value.elts):
                        if isinstance(t, ast.Name):
                            alias(t.id, v)
        elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            if isinstance(node.iter, (ast.Tuple, ast.List)):
                for v in node.iter.elts:
                    alias(node.target.id, v)
    return aliases


def _joined_attrs(cls: ast.ClassDef) -> set[str]:
    """Attrs with ``self.A.join(...)`` or alias ``t.join(...)`` anywhere."""
    joined: set[str] = set()
    for fn in class_methods(cls).values():
        aliases = _alias_map(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr == "join":
                recv = node.func.value
                attr = _self_attr(recv)
                if attr is not None:
                    joined.add(attr)
                elif isinstance(recv, ast.Name) and recv.id in aliases:
                    joined.update(aliases[recv.id])
    return joined


def _teardown_reachable_loads(cls: ast.ClassDef) -> set[str]:
    """Self attrs loaded in methods reachable from any teardown method."""
    methods = class_methods(cls)
    frontier = [n for n in _TEARDOWN if n in methods]
    seen: set[str] = set()
    loads: set[str] = set()
    while frontier:
        name = frontier.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        for node in ast.walk(methods[name]):
            dn = dotted(node) if isinstance(node, ast.Attribute) else None
            if dn and dn.startswith("self."):
                parts = dn.split(".")
                loads.add(parts[1])
                if len(parts) == 2 or parts[2:] == ["close"]:
                    pass
            if isinstance(node, ast.Call):
                cn = dotted(node.func)
                if cn and cn.startswith("self.") and cn.count(".") == 1:
                    frontier.append(cn.split(".", 1)[1])
    return loads


def run(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        for cls in module_classes(mod):
            findings.extend(_check_class(rel, cls))
    return findings


def _check_class(rel: str, cls: ast.ClassDef) -> list[Finding]:
    resources = _collect_resources(cls)
    if not resources:
        return []
    findings: list[Finding] = []
    methods = class_methods(cls)
    has_teardown = any(n in methods for n in _TEARDOWN)
    if not has_teardown:
        kinds = ", ".join(
            f"self.{attr} ({kind})"
            for attr, (kind, _, _) in sorted(resources.items())
        )
        findings.append(
            Finding(
                PASS_ID,
                rel,
                cls.lineno,
                f"class {cls.name} owns {kinds} but defines no "
                "close()/stop()/shutdown()",
            )
        )
    joined = _joined_attrs(cls)
    teardown_loads = _teardown_reachable_loads(cls) if has_teardown else set()
    for attr, (kind, daemon, line) in sorted(resources.items()):
        if kind == "thread":
            if attr not in joined:
                tag = "" if daemon else " (non-daemon!)"
                findings.append(
                    Finding(
                        PASS_ID,
                        rel,
                        line,
                        f"class {cls.name} stores a thread in self.{attr} "
                        f"but never joins it{tag}",
                    )
                )
        elif has_teardown and attr not in teardown_loads:
            findings.append(
                Finding(
                    PASS_ID,
                    rel,
                    line,
                    f"class {cls.name}: self.{attr} ({kind}) is never "
                    "released on the close()/stop() path",
                )
            )
    return findings
