"""deadline-hygiene: every unary RPC call site carries a timeout.

An RPC without a deadline turns a hung peer into a hung caller — and in
this control plane callers are heartbeat loops, CSI node operations and
gRPC handlers whose worker threads are a bounded pool.  Every unary
call on a generated stub must pass ``timeout=`` (a constant, or the
retry ladder's ``attempt.clamped(...)`` budget — both satisfy the
check).  Streaming watches (``WatchValues``) are exempt: an open-ended
watch is the contract, and cancellation is the caller's job.

Two detection shapes, matching how stubs are used in this tree:

- chained: ``REGISTRY.stub(channel).SetValue(req)``;
- named:   ``stub = REGISTRY.stub(channel); ...; stub.SetValue(req)``
  (any local assigned from a ``.stub(...)`` call);
- plus any call whose method name is a known unary RPC of oim.v1
  (catches helper-wrapped stubs).
"""

from __future__ import annotations

import ast

from tools.oimlint.core import Finding, SourceTree, dotted

PASS_ID = "deadline-hygiene"
DESCRIPTION = "unary RPC call sites must pass timeout="

# oim.v1 unary methods (doc/spec.md); WatchValues is a server stream.
UNARY_RPCS = {
    "SetValue", "GetValues", "MapVolume", "UnmapVolume", "ProvisionSlice",
    "CheckSlice", "GetTopology", "ListSlices",
}
# WatchValues (oim.v1) and Watch (etcd v3) are open-ended streams by
# contract; cancellation, not a deadline, bounds them.
STREAMING_RPCS = {"WatchValues", "Watch"}


def _has_timeout(node: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in node.keywords)


def _stub_locals(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted(node.value.func) or ""
            if callee.split(".")[-1] == "stub":
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out.add(target.id)
    return out


def run(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        stub_names = _stub_locals(mod)
        for node in ast.walk(mod):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            method = node.func.attr
            if method in STREAMING_RPCS:
                continue
            recv = node.func.value
            chained_stub = (
                isinstance(recv, ast.Call)
                and (dotted(recv.func) or "").split(".")[-1] == "stub"
            )
            named_stub = isinstance(recv, ast.Name) and recv.id in stub_names
            known_rpc = method in UNARY_RPCS
            if not (chained_stub or named_stub or known_rpc):
                continue
            if not _has_timeout(node):
                findings.append(
                    Finding(
                        PASS_ID,
                        rel,
                        node.lineno,
                        f"RPC {method}(...) without timeout= (pass a "
                        "constant or attempt.clamped(...))",
                    )
                )
    return findings
