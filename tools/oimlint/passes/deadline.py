"""deadline-hygiene: every unary RPC call site carries a timeout.

An RPC without a deadline turns a hung peer into a hung caller — and in
this control plane callers are heartbeat loops, CSI node operations and
gRPC handlers whose worker threads are a bounded pool.  Every unary
call on a generated stub must pass ``timeout=`` (a constant, or the
retry ladder's ``attempt.clamped(...)`` budget — both satisfy the
check).  Streaming watches (``WatchValues``) are exempt: an open-ended
watch is the contract, and cancellation is the caller's job.

Two detection shapes, matching how stubs are used in this tree:

- chained: ``REGISTRY.stub(channel).SetValue(req)``;
- named:   ``stub = REGISTRY.stub(channel); ...; stub.SetValue(req)``
  (any local assigned from a ``.stub(...)`` call);
- plus any call whose method name is a known unary RPC of oim.v1
  (catches helper-wrapped stubs).

The serve plane's HTTP clients carry the same obligation (ISSUE 11):
the router probes backends and splices failover streams with urllib
openers, the autoscaler streams peer weights, and oimctl drives both —
a urllib/socket call without a timeout turns a hung backend into a
hung router thread.  Flagged without ``timeout=``:

- ``urlopen(...)`` (bare, ``urllib.request.urlopen``, or any dotted
  ``*.urlopen`` — oimctl's ``_serve_urlopen`` wrapper binds the name
  ``urlopen`` locally, so the bare spelling is load-bearing);
- ``<opener>.open(...)`` — any receiver whose name contains "opener"
  (``self._opener.open``, ``opener(ctx).open``); plain file ``open``
  never matches;
- ``socket.create_connection(...)`` (positional timeout accepted: it
  is the second parameter);
- ``http.client.HTTPConnection(...)`` / ``HTTPSConnection(...)``
  constructors.
"""

from __future__ import annotations

import ast

from tools.oimlint.core import Finding, SourceTree, dotted

PASS_ID = "deadline-hygiene"
DESCRIPTION = "unary RPC call sites must pass timeout="

# oim.v1 unary methods (doc/spec.md); WatchValues is a server stream.
UNARY_RPCS = {
    "SetValue", "GetValues", "MapVolume", "UnmapVolume", "ProvisionSlice",
    "CheckSlice", "GetTopology", "ListSlices",
}
# WatchValues (oim.v1) and Watch (etcd v3) are open-ended streams by
# contract; cancellation, not a deadline, bounds them.
STREAMING_RPCS = {"WatchValues", "Watch"}


def _has_timeout(node: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in node.keywords)


def _http_violation(node: ast.Call) -> str | None:
    """The serve-plane HTTP rule: description of an unbounded HTTP/
    socket call, or None."""
    name = dotted(node.func) or ""
    parts = name.split(".")
    last = (
        node.func.attr
        if isinstance(node.func, ast.Attribute)
        else parts[-1]
    )
    desc = name or f"(...).{last}"
    # urlopen(url, data, timeout) / OpenerDirector.open(url, data,
    # timeout): the 3rd positional IS the timeout — honor it like the
    # create_connection branch honors its 2nd positional.
    url_bounded = _has_timeout(node) or len(node.args) >= 3
    if last == "urlopen":
        return None if url_bounded else f"{desc}(...)"
    if (
        last == "open"
        and len(parts) > 1
        and "opener" in parts[-2].lower()
    ):
        return None if url_bounded else f"{desc}(...)"
    # opener(ctx).open(...) — chained off a call whose callee mentions
    # an opener factory.
    if (
        last == "open"
        and isinstance(node.func, ast.Attribute)
        and isinstance(node.func.value, ast.Call)
        and "opener" in (dotted(node.func.value.func) or "").lower()
    ):
        return None if url_bounded else f"{desc}(...)"
    if last == "create_connection" and parts[0] in ("socket", "create_connection"):
        bounded = _has_timeout(node) or len(node.args) >= 2
        return None if bounded else f"{desc}(...)"
    if last == "HTTPConnection":
        # HTTPConnection(host, port, timeout): 3rd positional IS the
        # timeout.
        bounded = _has_timeout(node) or len(node.args) >= 3
        return None if bounded else f"{desc}(...)"
    if last == "HTTPSConnection":
        # Keyword only: the 3rd positional was key_file before 3.12 and
        # is rejected after it — a positional there never bounds.
        return None if _has_timeout(node) else f"{desc}(...)"
    return None


def _stub_locals(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted(node.value.func) or ""
            if callee.split(".")[-1] == "stub":
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out.add(target.id)
    return out


def run(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        stub_names = _stub_locals(mod)
        for node in ast.walk(mod):
            if not isinstance(node, ast.Call):
                continue
            http_desc = _http_violation(node)
            if http_desc is not None:
                findings.append(
                    Finding(
                        PASS_ID,
                        rel,
                        node.lineno,
                        f"HTTP/socket call {http_desc} without timeout= "
                        "(a hung peer becomes a hung caller thread)",
                    )
                )
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            method = node.func.attr
            if method in STREAMING_RPCS:
                continue
            recv = node.func.value
            chained_stub = (
                isinstance(recv, ast.Call)
                and (dotted(recv.func) or "").split(".")[-1] == "stub"
            )
            named_stub = isinstance(recv, ast.Name) and recv.id in stub_names
            known_rpc = method in UNARY_RPCS
            if not (chained_stub or named_stub or known_rpc):
                continue
            if not _has_timeout(node):
                findings.append(
                    Finding(
                        PASS_ID,
                        rel,
                        node.lineno,
                        f"RPC {method}(...) without timeout= (pass a "
                        "constant or attempt.clamped(...))",
                    )
                )
    return findings
