"""donation-safety: no use-after-donate at jitted call sites.

``jax.jit(..., donate_argnums=...)`` hands the donated buffer's memory
to XLA: after the call, the Python reference points at freed (or
reused) storage, and touching it raises — or worse, on some backends
silently reads garbage.  The serve engine leans on donation for every
cache buffer (six of its nine jitted functions donate), always in the
rebind idiom::

    self._cache, out = self._step(self._cache, ...)   # clean: rebound

This pass finds the two ways that idiom breaks:

1. **use-after-donate** — a variable passed at a donated position is
   read later in the same function without having been rebound (by the
   call's own result, or by any intervening assignment);
2. **double donation** — the same variable passed at two donated
   positions of one call: XLA would alias both parameters to one
   buffer and the second write clobbers the first.

Call sites are matched through the shared jit-site resolver
(``jaxsites``): direct bindings, ``self._x``-attribute bindings,
``partial(...)`` wrappings, and cross-module jit factories
(``step = make_train_step(...)``).

Scope model: every function (nested defs included) is analyzed as its
own scope, and lambda bodies are skipped entirely — a lambda cannot
rebind, so the forwarding idiom ``step_fn = lambda s, b: jit_step(s,
base, b)`` must not leak its shadowing params into the enclosing
scope's donated-name tracking.  A binding assigned different jit
wrappings in mutually-exclusive branches (the engine's plain/spec/
spec-model ``self._decode``) is disambiguated at each call site by the
wrapped function's positional arity.

Over-approximations, documented: statement order stands in for
execution order, so a donate in an ``if`` arm and a read in the
``else`` arm reads as use-after-donate (waive it); reads *before* an
un-rebound donating call inside a loop body are missed (they re-execute
after the donation on iteration two), as are closure reads from a
sibling nested function.
"""

from __future__ import annotations

import ast

from tools.oimlint.core import Finding, SourceTree, dotted
from tools.oimlint.passes import jaxsites

PASS_ID = "donation-safety"
DESCRIPTION = "donated jit buffers must be rebound, never re-read"


def _functions(mod: ast.Module):
    """Every function scope — module-level, methods, and nested defs,
    each analyzed on its own (a nested def's params shadow the outer
    names, and its body does not execute in statement order relative to
    the enclosing function)."""
    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _statements(fn: ast.AST):
    """``fn``'s own statements in document order, NOT descending into
    nested function/class scopes (those are separate scopes yielded by
    ``_functions``)."""
    out = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            if isinstance(child, ast.stmt):
                out.append(child)
            visit(child)

    visit(fn)
    return out


def _own_nodes(stmt: ast.stmt):
    """Walk one statement's own expressions WITHOUT descending into
    child statements (an ``if``'s body statements appear separately in
    the document-order list; re-walking them here would double-count
    every nested load and call) and WITHOUT descending into lambda
    bodies (lambda params shadow; a lambda cannot rebind a donated
    buffer, and its forwarding calls belong to no statement order)."""
    stack: list[ast.AST] = []
    for child in ast.iter_child_nodes(stmt):
        if not isinstance(child, ast.stmt):
            stack.append(child)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.stmt):
                stack.append(child)


_META_ATTRS = {"shape", "ndim", "dtype", "size"}


def _loads_stores(stmt: ast.stmt):
    """(loads, stores) dotted names of one statement's own expressions.
    Pure metadata chains (``buf.shape``/``.ndim``/``.dtype``/``.size``)
    are NOT loads — array metadata survives donation by design, so
    reading it off a donated buffer is legal."""
    loads: list[tuple[str, int]] = []
    stores: list[tuple[str, int]] = []

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.Lambda):
            return  # shadowing scope, skipped (see _own_nodes)
        if isinstance(node, ast.stmt) and node is not stmt:
            return  # child statements are separate entries
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _META_ATTRS
            and isinstance(node.ctx, ast.Load)
            and dotted(node.value) is not None
        ):
            return  # metadata read: survives donation
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = dotted(node)
            if name is not None:
                if isinstance(node.ctx, ast.Store):
                    stores.append((name, node.lineno))
                elif isinstance(node.ctx, (ast.Load, ast.Del)):
                    loads.append((name, node.lineno))
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(stmt)
    return loads, stores


def _donated_names(
    call: ast.Call, matched: list[jaxsites.JitSite]
) -> list[tuple[object, str]]:
    """(position-or-kwarg, dotted name) for donated args that are plain
    variables (literals/fresh expressions at donated positions have no
    later readers by construction).  Covers donate_argnums, donate
    _argnames resolved through the wrapped signature, and keyword call
    sites matching a donate_argnames entry."""
    positions = sorted({
        pos for site in matched for pos in site.donated_positions()
    })
    by_name = {n for site in matched for n in site.donate_names}
    out: list[tuple[object, str]] = []
    for pos in positions:
        if pos < len(call.args):
            name = dotted(call.args[pos])
            if name:
                out.append((pos, name))
    for kw in call.keywords:
        if kw.arg in by_name:
            name = dotted(kw.value)
            if name:
                out.append((f"{kw.arg}=", name))
    return out


def _rebound_targets(stmt: ast.stmt, call: ast.Call) -> set[str]:
    """Names the statement containing ``call`` rebinds from the call's
    result (the ``cache, out = self._step(cache, ...)`` idiom)."""
    if isinstance(stmt, ast.Assign) and stmt.value is call:
        out: set[str] = set()
        for target in stmt.targets:
            elts = (
                target.elts
                if isinstance(target, (ast.Tuple, ast.List))
                else [target]
            )
            for elt in elts:
                if isinstance(elt, ast.Starred):
                    elt = elt.value
                name = dotted(elt)
                if name:
                    out.add(name)
        return out
    if (
        isinstance(stmt, ast.AnnAssign)
        and stmt.value is call
        and (name := dotted(stmt.target))
    ):
        return {name}
    return set()


def _check_function(
    rel: str, fn: ast.AST, donating: dict[str, list[jaxsites.JitSite]]
) -> list[Finding]:
    findings: list[Finding] = []
    stmts = _statements(fn)
    per_stmt = [_loads_stores(s) for s in stmts]

    for idx, stmt in enumerate(stmts):
        for call in _own_nodes(stmt):
            if not isinstance(call, ast.Call):
                continue
            callee = dotted(call.func)
            variants = donating.get(callee or "")
            if not variants:
                continue
            matched = jaxsites.sites_for_call(variants, len(call.args))
            names = _donated_names(call, matched)

            seen: dict[str, object] = {}
            for pos, name in names:
                if name in seen:
                    findings.append(Finding(
                        PASS_ID, rel, call.lineno,
                        f"{callee}(...): variable '{name}' passed at two "
                        f"donated positions ({seen[name]} and {pos}) — "
                        "XLA aliases both to one buffer",
                    ))
                else:
                    seen[name] = pos

            rebound = _rebound_targets(stmt, call)
            for _pos, name in names:
                if name in rebound:
                    continue
                flagged = False
                for later_idx in range(idx + 1, len(stmts)):
                    loads, stores = per_stmt[later_idx]
                    for load_name, load_line in loads:
                        if load_name == name and not flagged:
                            findings.append(Finding(
                                PASS_ID, rel, load_line,
                                f"use-after-donate: '{name}' was donated "
                                f"to {callee}(...) at line {call.lineno} "
                                "and read again without being rebound "
                                "(rebind it from the call's result)",
                            ))
                            flagged = True
                    if any(s == name for s, _ in stores):
                        break
                    if flagged:
                        break
    return findings


def run(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    factories = jaxsites.tree_factories(tree)
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        donating = resolve_donating(tree, rel, factories)
        if not donating:
            continue
        for fn in _functions(mod):
            findings.extend(_check_function(rel, fn, donating))
    return findings


def resolve_donating(
    tree: SourceTree, rel: str, factories: dict[str, jaxsites.JitSite]
) -> dict[str, list[jaxsites.JitSite]]:
    """Bindings in ``rel`` wrapping a donating jit (shared with the
    analyzer tests)."""
    return jaxsites.resolve(tree, rel, factories).donating_bindings()
