"""lock-order: whole-tree lock-acquisition graph + deadlock cycles.

Every shipped review round has surfaced a serve-plane lock bug by hand
(the ``_error_lock`` check-then-set race, the ConnCache close-latch
leak, the stale-cancel ``_finish`` race).  The lock-discipline pass
checks what happens *under* a lock; nothing checked lock *ordering*.
This pass is the ``go vet``-grade half of that gap (the runtime
sanitizer, ``oim_tpu/common/locksan.py``, is the race-detector half):

1. resolve every lock attribute per class through the shared
   ``locksites`` resolver (``threading.Lock/RLock/Condition`` and the
   locksan factory spellings, instance- or ClassDef-level, including
   composition like ``with self._host.lock:`` resolved by unique
   attribute name across the tree);
2. build the acquisition graph: an edge ``A → B`` means some thread
   acquires B while holding A — from direct ``with``-statement nesting,
   and from one level of intra-class call resolution (holding A,
   ``self.m()`` is called and ``m`` acquires B somewhere in its body;
   this is also how ``*_locked``-convention callees contribute edges:
   the caller holds the guard, the callee's own nested ``with`` blocks
   land as edges from everything the caller holds);
3. report every cycle as a potential deadlock, citing BOTH acquisition
   chains (method names, not line numbers, so baseline keys stay
   stable), and every call that re-acquires a non-reentrant lock the
   caller already holds (self-deadlock, the ``Lock``-not-``RLock``
   class of hang).

Known approximations, deliberate (the jaxsites contract — documented,
never silent): call resolution is one level deep and intra-class only
(a cross-class call chain that inverts two locks is invisible here —
that is exactly what the runtime sanitizer exists for); a callee that
acquires a lock only on a branch the holding caller never reaches
still contributes the edge (over-approximation: waiver material, and
waivers carry justifications).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tools.oimlint.core import Finding, SourceTree, class_methods, module_classes
from tools.oimlint.passes import locksites
from tools.oimlint.passes.locksites import HeldLockWalker, LockNode

PASS_ID = "lock-order"
DESCRIPTION = "lock-acquisition graph must be cycle-free (deadlock check)"

_LIFECYCLE_SKIP = {"__init__", "__new__", "__post_init__"}


@dataclass(frozen=True)
class _Edge:
    """One witnessed ``held → acquired`` pair."""

    src: LockNode
    dst: LockNode
    rel: str
    line: int
    where: str  # "Engine._finish" — method names only, baseline-stable
    via_call: str | None = None  # callee name when from call resolution


class _AcqScan(HeldLockWalker):
    """Per-method acquisition events: direct nesting + self calls."""

    def __init__(self, cls_name, own_locks, index):
        super().__init__(cls_name, own_locks, index)
        # (held_snapshot, acquired, line)
        self.acquires: list[tuple[tuple[LockNode, ...], LockNode, int]] = []
        # (held_snapshot, callee, line)
        self.calls: list[tuple[tuple[LockNode, ...], str, int]] = []

    def on_acquire(self, node: LockNode, line: int) -> None:
        self.acquires.append((tuple(self.held), node, line))

    def on_self_call(self, method: str, line: int) -> None:
        if self.held:
            self.calls.append((tuple(self.held), method, line))


def _scan_class(rel: str, cls: ast.ClassDef, index) -> list[_Edge]:
    own_locks = locksites.class_lock_attrs(cls)
    methods = class_methods(cls)
    scans: dict[str, _AcqScan] = {}
    for name, fn in methods.items():
        scan = _AcqScan(cls.name, own_locks, index)
        for stmt in fn.body:
            scan.visit(stmt)
        scans[name] = scan

    edges: list[_Edge] = []
    for name, scan in scans.items():
        if name in _LIFECYCLE_SKIP:
            continue  # constructors are single-threaded by contract
        where = f"{cls.name}.{name}"
        # Direct with-nesting.
        for held, acquired, line in scan.acquires:
            for h in held:
                if h.name != acquired.name:
                    edges.append(_Edge(h, acquired, rel, line, where))
        # One level of intra-class call resolution: holding H, calling
        # self.m() contributes H → every lock m acquires anywhere.
        for held, callee, line in scan.calls:
            callee_scan = scans.get(callee)
            if callee_scan is None:
                continue
            for _, acquired, _ in callee_scan.acquires:
                for h in held:
                    edges.append(
                        _Edge(h, acquired, rel, line, where, via_call=callee)
                    )
    return edges


def _witness(edge: _Edge) -> str:
    via = f" via self.{edge.via_call}()" if edge.via_call else ""
    return f"{edge.where}{via}: holds {edge.src.name}, acquires {edge.dst.name}"


def _sccs(nodes: set[str], adj: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def _cycle_in_scc(comp: list[str], adj: dict[str, set[str]]) -> list[str]:
    """One concrete cycle inside a (≥2-node) SCC, as a node path."""
    members = set(comp)
    start = min(comp)
    # BFS from start back to start, restricted to the SCC.
    parents: dict[str, str] = {}
    frontier = [start]
    seen = {start}
    while frontier:
        nxt = []
        for v in frontier:
            for w in sorted(adj.get(v, ())):
                if w not in members:
                    continue
                if w == start:
                    chain = [v]
                    while chain[-1] != start:
                        chain.append(parents[chain[-1]])
                    chain.reverse()  # start -> ... -> v
                    return chain + [start]
                if w not in seen:
                    seen.add(w)
                    parents[w] = v
                    nxt.append(w)
        frontier = nxt
    return comp + [comp[0]]  # unreachable for a true SCC; defensive


def run(tree: SourceTree) -> list[Finding]:
    index = locksites.lock_index(tree)
    edges: list[_Edge] = []
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        for cls in module_classes(mod):
            edges.extend(_scan_class(rel, cls, index))

    findings: list[Finding] = []

    # Self-deadlock: a call chain re-acquiring a non-reentrant lock.
    # (RLocks and Conditions re-enter; with-nesting on the same name is
    # excluded at edge construction — only call resolution lands here.)
    seen_re: set[str] = set()
    for e in edges:
        if e.src.name == e.dst.name and e.src.kind == "Lock" and e.via_call:
            msg = (
                f"{e.where}: calls self.{e.via_call}() which re-acquires "
                f"non-reentrant {e.src.name} already held (self-deadlock)"
            )
            if msg not in seen_re:
                seen_re.add(msg)
                findings.append(Finding(PASS_ID, e.rel, e.line, msg))

    # Cycle detection over distinct-lock edges.
    first: dict[tuple[str, str], _Edge] = {}
    for e in edges:
        if e.src.name != e.dst.name:
            first.setdefault((e.src.name, e.dst.name), e)
    adj: dict[str, set[str]] = {}
    nodes: set[str] = set()
    for (a, b) in first:
        adj.setdefault(a, set()).add(b)
        nodes.update((a, b))

    reported_pairs: set[tuple[str, str]] = set()
    for (a, b), e_ab in sorted(first.items()):
        if a < b and (b, a) in first:
            e_ba = first[(b, a)]
            reported_pairs.add((a, b))
            findings.append(
                Finding(
                    PASS_ID,
                    e_ab.rel,
                    e_ab.line,
                    f"potential deadlock: {a} -> {b} [{_witness(e_ab)}] "
                    f"vs {b} -> {a} [{_witness(e_ba)}]",
                )
            )

    for comp in _sccs(nodes, adj):
        if len(comp) < 2:
            continue
        members = set(comp)
        if any(
            (a, b) in reported_pairs
            for a in members
            for b in members
            if a < b
        ):
            continue  # already reported as a 2-cycle
        path = _cycle_in_scc(comp, adj)
        hops = [
            _witness(first[(path[i], path[i + 1])])
            for i in range(len(path) - 1)
            if (path[i], path[i + 1]) in first
        ]
        e0 = first[(path[0], path[1])]
        findings.append(
            Finding(
                PASS_ID,
                e0.rel,
                e0.line,
                "potential deadlock cycle: "
                + " -> ".join(path)
                + " [" + "; ".join(hops) + "]",
            )
        )
    return findings
