"""lock-discipline: shared-attribute and hold-while-blocking checks.

Two rules, both scoped to what this codebase actually does with
threads (background heartbeat/drain/watch loops inside classes whose
public methods are called from gRPC/HTTP worker pools):

1. **Unguarded shared attribute** — in any class that spawns a
   ``threading.Thread`` targeting one of its own methods, an instance
   attribute mutated BOTH on the thread path (the target method and
   everything it calls through ``self``) AND in some other method is
   shared mutable state; every mutation site must hold one of the
   class's locks (an attribute assigned ``threading.Lock()`` /
   ``RLock()`` / ``Condition()``).  Methods named ``*_locked`` are
   treated as guarded by convention (they document the caller holds
   the lock).

2. **Blocking call while holding a lock** — inside a ``with
   self.<lock>:`` block, a call that can block on the network or the
   clock (``time.sleep``, socket ``connect``/``sendall``/``recv``/
   ``readline``, JSON-RPC ``.invoke``, dialing an ``Agent``/``Client``,
   unary registry/controller RPCs) serializes every other thread
   contending for that lock behind a peer's latency.  ``Condition
   .wait`` is exempt (it releases the lock).  Intentional cases (e.g. a
   client that serializes one roundtrip per connection by design) carry
   a ``# oimlint: disable=lock-discipline`` waiver with a justification.
"""

from __future__ import annotations

import ast

from tools.oimlint.core import (
    Finding,
    SourceTree,
    call_name,
    class_methods,
    dotted,
    keyword_arg,
    module_classes,
)

PASS_ID = "lock-discipline"
DESCRIPTION = "shared attrs need locks; no blocking calls while locked"

# The threading ctors plus the locksan sanitizer factory spellings
# (oim_tpu/common/locksan.py) — adopting the sanitizer must not blind
# this pass to the serve plane's locks.
_LOCK_CTORS = (
    "Lock", "RLock", "Condition", "new_lock", "new_rlock", "new_condition",
)
_MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update", "pop",
    "popleft", "popitem", "clear", "remove", "discard", "setdefault",
}
# Calls that can block on a peer or the clock (tuned to this tree).
_BLOCKING_DOTTED = {"time.sleep", "select.select"}
_BLOCKING_ATTRS = {
    "sendall", "recv", "recvfrom", "readline", "connect", "accept",
    "invoke",
}
_BLOCKING_CTORS = {"Agent", "Client"}
_BLOCKING_RPCS = {
    "SetValue", "GetValues", "MapVolume", "UnmapVolume", "ProvisionSlice",
    "CheckSlice", "GetTopology", "ListSlices",
}
# Waits that RELEASE the lock they are called under.
_EXEMPT_ATTRS = {"wait", "wait_for"}

_LIFECYCLE_SKIP = {"__init__", "__new__", "__post_init__"}


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    locks: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = call_name(node.value) or ""
            if name.split(".")[-1] in _LOCK_CTORS:
                for target in node.targets:
                    t = dotted(target)
                    if t and t.startswith("self.") and t.count(".") == 1:
                        locks.add(t.split(".", 1)[1])
    return locks


def _thread_targets(cls: ast.ClassDef) -> set[str]:
    """Names of ``self`` methods used as ``threading.Thread`` targets."""
    targets: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            name = call_name(node) or ""
            if name.split(".")[-1] != "Thread":
                continue
            target = keyword_arg(node, "target")
            t = dotted(target) if target is not None else None
            if t and t.startswith("self.") and t.count(".") == 1:
                targets.add(t.split(".", 1)[1])
    return targets


def _self_calls(fn: ast.FunctionDef) -> set[str]:
    out: set[str] = set()
    for node in _walk_scope(fn):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and name.startswith("self.") and name.count(".") == 1:
                out.add(name.split(".", 1)[1])
    return out


def _walk_scope(fn: ast.AST):
    """Walk a method body without descending into nested classes (whose
    ``self`` is a different object); nested functions/lambdas close over
    the outer ``self`` and ARE descended into."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _MethodScan(ast.NodeVisitor):
    """Mutations of ``self.X`` and blocking calls, with lock-held state."""

    def __init__(self, locks: set[str]):
        self.locks = locks
        self.held: list[str] = []
        # attr -> list[(line, guarded)]
        self.mutations: dict[str, list[tuple[int, bool]]] = {}
        # (line, description, lock) blocking calls under a held lock
        self.blocking: list[tuple[int, str, str]] = []

    # -- helpers -----------------------------------------------------------

    def _mutate(self, target: ast.AST, line: int) -> None:
        name = dotted(target)
        if name and name.startswith("self.") and name.count(".") == 1:
            attr = name.split(".", 1)[1]
            if attr in self.locks:
                return
            self.mutations.setdefault(attr, []).append(
                (line, bool(self.held))
            )

    # -- scope fencing -----------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass  # different ``self``

    # -- lock tracking -----------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        entered = []
        for item in node.items:
            expr = item.context_expr
            name = dotted(expr)
            if name is None and isinstance(expr, ast.Call):
                name = dotted(expr.func)  # with self._lock.acquire_timeout()
            if (
                name
                and name.startswith("self.")
                and name.split(".")[1] in self.locks
            ):
                entered.append(name.split(".")[1])
            self.visit(expr)
        self.held.extend(entered)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(entered):]

    # -- mutation sites ----------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    self._mutate_store(elt, node.lineno)
            else:
                self._mutate_store(target, node.lineno)
        self.visit(node.value)

    def _mutate_store(self, target: ast.AST, line: int) -> None:
        if isinstance(target, ast.Subscript):
            self._mutate(target.value, line)  # self.X[k] = v mutates X
        else:
            self._mutate(target, line)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mutate_store(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._mutate_store(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._mutate_store(target, node.lineno)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func) or ""
        parts = name.split(".")
        # self.X.append(...) mutates X
        if (
            len(parts) == 3
            and parts[0] == "self"
            and parts[2] in _MUTATORS
        ):
            self._mutate(node.func.value, node.lineno)
        if self.held:
            desc = self._blocking_desc(node, name, parts)
            if desc:
                self.blocking.append((node.lineno, desc, self.held[-1]))
        self.generic_visit(node)

    @staticmethod
    def _blocking_desc(node: ast.Call, name: str, parts: list[str]) -> str | None:
        if name in _BLOCKING_DOTTED:
            return f"{name}(...)"
        last = parts[-1]
        if last in _EXEMPT_ATTRS:
            return None
        if len(parts) > 1 and last in _BLOCKING_ATTRS:
            return f"{name}(...)"
        if len(parts) > 1 and last in _BLOCKING_RPCS:
            return f"{name}(...) RPC"
        if len(parts) == 1 and last in _BLOCKING_CTORS:
            return f"{last}(...) dial"
        return None


def run(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        for cls in module_classes(mod):
            findings.extend(_check_class(rel, cls))
    return findings


def _check_class(rel: str, cls: ast.ClassDef) -> list[Finding]:
    locks = _lock_attrs(cls)
    methods = class_methods(cls)
    targets = _thread_targets(cls) & set(methods)

    # Thread-path closure over self-calls.
    thread_methods: set[str] = set()
    frontier = list(targets)
    while frontier:
        name = frontier.pop()
        if name in thread_methods or name not in methods:
            continue
        thread_methods.add(name)
        frontier.extend(_self_calls(methods[name]))

    findings: list[Finding] = []
    per_method: dict[str, _MethodScan] = {}
    for name, fn in methods.items():
        scan = _MethodScan(locks)
        for stmt in fn.body:
            scan.visit(stmt)
        per_method[name] = scan
        for line, desc, lock in scan.blocking:
            findings.append(
                Finding(
                    PASS_ID,
                    rel,
                    line,
                    f"{cls.name}.{name}: blocking call {desc} while "
                    f"holding self.{lock}",
                )
            )

    if not targets:
        return findings

    # Attributes mutated on the thread path AND elsewhere.
    def mutated_attrs(names: set[str]) -> dict[str, list[tuple[str, int, bool]]]:
        out: dict[str, list[tuple[str, int, bool]]] = {}
        for name in names:
            if name in _LIFECYCLE_SKIP:
                continue
            guarded_by_convention = name.endswith("_locked")
            for attr, sites in per_method[name].mutations.items():
                for line, guarded in sites:
                    out.setdefault(attr, []).append(
                        (name, line, guarded or guarded_by_convention)
                    )
        return out

    on_thread = mutated_attrs(thread_methods)
    elsewhere = mutated_attrs(set(methods) - thread_methods)
    for attr in sorted(set(on_thread) & set(elsewhere)):
        sites = on_thread[attr] + elsewhere[attr]
        unguarded = [(m, line) for m, line, guarded in sites if not guarded]
        if not unguarded:
            continue
        thread_side = ", ".join(sorted({m for m, _, _ in on_thread[attr]}))
        for method, line in sorted(unguarded, key=lambda s: s[1]):
            findings.append(
                Finding(
                    PASS_ID,
                    rel,
                    line,
                    f"{cls.name}.{method}: shared attribute self.{attr} "
                    f"mutated without a class lock (also mutated on the "
                    f"thread path: {thread_side})",
                )
            )
    return findings
