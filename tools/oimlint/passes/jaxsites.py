"""Shared jit-site resolver for the jaxvet pass family.

The three JAX hot-path passes (``donation-safety``,
``host-sync-discipline``, ``retrace-risk``) all need the same map: which
variables in a module are bound to a ``jax.jit``-wrapped callable, what
the wrapped function is, and which argument positions are donated or
static.  This module builds that map once per file, handling the
binding shapes this tree actually uses:

- direct:        ``self._cow = jax.jit(_cow_block, donate_argnums=(0,))``
- partial-wrapped: ``self._decode = jax.jit(partial(_decode_chunk,
  cfg=cfg, ...), donate_argnums=(1, 3, 4))`` — the partial's keywords
  are trace-time constants and count as static;
- conditional:   ``self._admit_d = (jax.jit(...) if draft else None)``;
- factory:       ``def make_train_step(...): return jax.jit(step,
  donate_argnums=(0,))`` — a *jit factory*; a later
  ``step = make_train_step(cfg, mesh)`` (in any scanned module) binds a
  jit site with the factory's donation/static signature.

Known over-approximations, deliberate (baseline/waiver material, never
silent): attribute bindings (``self._decode``) are resolved module-wide
— two classes in one module binding the same attribute to different jit
signatures would be merged; factories are matched by bare function name
across modules without import tracking.  Neither shape exists in this
tree today, and the resolver tests pin the supported ones.
"""

from __future__ import annotations

import ast
import dataclasses
from dataclasses import dataclass, field

from tools.oimlint.core import SourceTree, dotted

# Callee spellings that construct a jitted callable.
_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
# Pallas kernel invocations: each pl.pallas_call(...) constructs a
# fresh wrapped callable (its own trace cache), exactly like jax.jit —
# the retrace pass flags per-iteration construction.
_PALLAS_NAMES = {"pl.pallas_call", "pallas_call", "pallas.pallas_call"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


@dataclass(frozen=True)
class JitSite:
    """One resolved jit wrapping: what it wraps and how."""

    binding: str | None  # "self._decode" / "step_fn"; None when unbound
    target: str | None   # bare name of the wrapped callable, if resolvable
    donate: tuple[int, ...] = ()
    static: tuple[int, ...] = ()
    static_names: tuple[str, ...] = ()
    # donate_argnames params: DONATED (and traced) — never static.
    donate_names: tuple[str, ...] = ()
    bound_kwargs: tuple[str, ...] = ()  # partial(...) keywords: static
    line: int = 0
    # Positional parameters of the wrapped callable when its def is in
    # the same module — how call sites disambiguate a binding that is
    # assigned different jit wrappings in mutually-exclusive branches
    # (the engine's ``self._decode`` is plain/spec/spec-model depending
    # on config, with different arities and donate tuples), and how
    # donate_argnames resolve to positional indices.
    target_arity: int | None = None
    target_params: tuple[str, ...] = ()

    def donated_positions(self) -> tuple[int, ...]:
        """donate_argnums plus donate_argnames resolved through the
        wrapped signature (names without a known signature stay
        name-matched at keyword call sites only)."""
        out = set(self.donate)
        for name in self.donate_names:
            if name in self.target_params:
                out.add(self.target_params.index(name))
        return tuple(sorted(out))


@dataclass
class ModuleSites:
    """All jit sites of one module, indexed for the passes.

    ``by_binding`` maps each bound name to EVERY site assigned to it —
    conditional rebinding (``self._decode = jax.jit(A) ... else
    jax.jit(B)``) is the engine's idiom, and a pass picks the variant
    whose ``target_arity`` matches the call site."""

    by_binding: dict[str, list[JitSite]] = field(default_factory=dict)
    factories: dict[str, JitSite] = field(default_factory=dict)
    all_sites: list[JitSite] = field(default_factory=list)

    def donating_bindings(self) -> dict[str, list[JitSite]]:
        out = {
            b: [s for s in sites if s.donate or s.donate_names]
            for b, sites in self.by_binding.items()
        }
        return {b: sites for b, sites in out.items() if sites}


def sites_for_call(sites: list[JitSite], n_args: int) -> list[JitSite]:
    """The binding variants a call with ``n_args`` positional args can
    reach: exact arity matches when any variant's arity is known and
    matches, every variant otherwise (over-approximation beats silence
    when the wrapped def lives in another module)."""
    matched = [s for s in sites if s.target_arity == n_args]
    return matched or sites


def _int_tuple(node: ast.expr | None) -> tuple[int, ...]:
    """Literal ``donate_argnums``/``static_argnums`` value; non-literal
    (computed) values resolve to () — an under-approximation the passes
    accept over guessing."""
    if node is None:
        return ()
    try:
        value = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(value, int):
        return (value,)
    if isinstance(value, (tuple, list)) and all(
        isinstance(v, int) for v in value
    ):
        return tuple(value)
    return ()


def _str_tuple(node: ast.expr | None) -> tuple[str, ...]:
    if node is None:
        return ()
    try:
        value = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(value, str):
        return (value,)
    if isinstance(value, (tuple, list)) and all(
        isinstance(v, str) for v in value
    ):
        return tuple(value)
    return ()


def is_jit_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and (dotted(node.func) or "") in _JIT_NAMES
    )


def is_pallas_call(node: ast.AST) -> bool:
    """A ``pl.pallas_call(...)`` construction site.  Like ``jax.jit``,
    each construction is a brand-new callable with its own trace
    cache — safe at module level, inside ``__init__`` tables, or in a
    function body that itself only runs under an enclosing jit trace
    (the kernel-wrapper idiom: ``ops/flash_attention.py``,
    ``ops/paged_attention.py``), but a per-iteration rebuild in a
    python loop pays the lowering every pass."""
    return (
        isinstance(node, ast.Call)
        and (dotted(node.func) or "") in _PALLAS_NAMES
    )


def parse_jit_call(node: ast.Call, binding: str | None) -> JitSite:
    """One ``jax.jit(...)`` call → a :class:`JitSite` (partial unwrapped,
    argnums parsed when literal)."""
    donate = static = ()
    static_names: tuple[str, ...] = ()
    donate_names: tuple[str, ...] = ()
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            donate = _int_tuple(kw.value)
        elif kw.arg == "static_argnums":
            static = _int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            static_names = static_names + _str_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            donate_names = donate_names + _str_tuple(kw.value)
    target = None
    bound_kwargs: tuple[str, ...] = ()
    if node.args:
        wrapped = node.args[0]
        if (
            isinstance(wrapped, ast.Call)
            and (dotted(wrapped.func) or "") in _PARTIAL_NAMES
        ):
            bound_kwargs = tuple(
                kw.arg for kw in wrapped.keywords if kw.arg
            )
            wrapped = wrapped.args[0] if wrapped.args else wrapped
        name = dotted(wrapped)
        if name:
            target = name.split(".")[-1]
    return JitSite(
        binding=binding,
        target=target,
        donate=donate,
        static=static,
        static_names=static_names,
        donate_names=donate_names,
        bound_kwargs=bound_kwargs,
        line=node.lineno,
    )


def _jit_value(node: ast.expr) -> ast.Call | None:
    """The jit call inside an assignment RHS: direct, or either arm of a
    conditional expression (``jax.jit(...) if draft else None``)."""
    if is_jit_call(node):
        return node  # type: ignore[return-value]
    if isinstance(node, ast.IfExp):
        for arm in (node.body, node.orelse):
            if is_jit_call(arm):
                return arm  # type: ignore[return-value]
    return None


def _pos_params(fn: ast.FunctionDef) -> tuple[str, ...]:
    return tuple(a.arg for a in fn.args.posonlyargs + fn.args.args)


def collect_module_sites(mod: ast.Module) -> ModuleSites:
    """Every jit site in ``mod``: bound (assignments), factory
    (functions returning a jit), and unbound (the rest)."""
    sites = ModuleSites()
    bound_calls: set[int] = set()
    params_map = {
        node.name: _pos_params(node)
        for node in ast.walk(mod)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }

    def parsed(call: ast.Call, binding: str | None) -> JitSite:
        site = parse_jit_call(call, binding=binding)
        if site.target in params_map:
            params = params_map[site.target]
            site = dataclasses.replace(
                site, target_arity=len(params), target_params=params
            )
        return site

    for node in ast.walk(mod):
        if isinstance(node, ast.Assign):
            call = _jit_value(node.value)
            if call is None:
                continue
            bound_calls.add(id(call))
            for target in node.targets:
                name = dotted(target)
                if name is None:
                    continue
                site = parsed(call, binding=name)
                sites.by_binding.setdefault(name, []).append(site)
                sites.all_sites.append(site)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(node):
                if isinstance(child, ast.Return) and child.value is not None:
                    call = _jit_value(child.value)
                    if call is not None:
                        bound_calls.add(id(call))
                        site = parsed(call, binding=None)
                        sites.factories[node.name] = site
                        sites.all_sites.append(site)

    for node in ast.walk(mod):
        if is_jit_call(node) and id(node) not in bound_calls:
            sites.all_sites.append(parsed(node, binding=None))  # type: ignore[arg-type]
    return sites


def tree_factories(tree: SourceTree) -> dict[str, JitSite]:
    """Jit factories across every scanned file, by bare function name —
    the cross-module half of the resolver (``step =
    make_train_step(...)`` in one module, the factory in another).
    Memoized on the tree instance: all three jaxvet passes call this
    per run, and the full-tree walk must be paid once, not three times
    (the same pattern as the tree's own AST cache)."""
    cached = getattr(tree, "_jaxsites_factories", None)
    if cached is not None:
        return cached
    out: dict[str, JitSite] = {}
    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        out.update(_module_sites_cached(tree, rel).factories)
    tree._jaxsites_factories = out  # type: ignore[attr-defined]
    return out


def _module_sites_cached(tree: SourceTree, rel: str) -> ModuleSites:
    cache = getattr(tree, "_jaxsites_modules", None)
    if cache is None:
        cache = {}
        tree._jaxsites_modules = cache  # type: ignore[attr-defined]
    if rel not in cache:
        mod = tree.tree(rel)
        cache[rel] = (
            ModuleSites() if mod is None else collect_module_sites(mod)
        )
    return cache[rel]


def resolve(
    tree: SourceTree, rel: str, factories: dict[str, JitSite] | None = None
) -> ModuleSites:
    """``rel``'s jit sites, with bindings assigned from a known factory
    (``fn = make_train_step(...)``) folded in when ``factories`` (from
    :func:`tree_factories`) is supplied."""
    mod = tree.tree(rel)
    sites = ModuleSites()
    if mod is None:
        return sites
    sites = _module_sites_cached(tree, rel)
    if factories:
        for node in ast.walk(mod):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            callee = (dotted(node.value.func) or "").split(".")[-1]
            if callee not in factories:
                continue
            proto = factories[callee]
            for target in node.targets:
                name = dotted(target)
                if name is None or name in sites.by_binding:
                    continue
                site = dataclasses.replace(
                    proto, binding=name, line=node.lineno
                )
                sites.by_binding[name] = [site]
                sites.all_sites.append(site)
    return sites


# -- shared hot-path designation --------------------------------------------

HOTPATH_MARKER = "# oimlint: hotpath"

# Per-module fallback table for hot-path functions in files that cannot
# carry markers (generated code, vendored snippets).  repo-relative path
# → function names.  Empty today: the serve engine declares its spine
# in-line with markers, which keeps the declaration next to the code it
# governs.
HOTPATH_TABLE: dict[str, tuple[str, ...]] = {}


def hotpath_functions(
    tree: SourceTree, rel: str, table: dict[str, tuple[str, ...]] | None = None
) -> dict[str, ast.FunctionDef]:
    """Functions in ``rel`` designated hot-path: a ``# oimlint: hotpath``
    marker on the ``def`` line or the line above, or a HOTPATH_TABLE
    entry.  Returns {name: FunctionDef}."""
    mod = tree.tree(rel)
    if mod is None:
        return {}
    lines = tree.lines(rel)
    names = set((table if table is not None else HOTPATH_TABLE).get(rel, ()))
    out: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mod):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        marked = node.name in names
        for idx in (node.lineno - 1, node.lineno - 2):
            if 0 <= idx < len(lines) and HOTPATH_MARKER in lines[idx]:
                marked = True
        if marked:
            out[node.name] = node  # type: ignore[assignment]
    return out
