"""protocol-drift: client ↔ fake agent ↔ protocol doc agreement.

The reference kept its wire contract honest with a CI job that
extracted protobuf from ``spec.md`` and diffed it against ``oim.proto``
(reference Makefile:85-103).  The tpu-agent's JSON-RPC protocol has no
proto to diff, so this pass rebuilds the same gate from its three
sources of truth:

- **used**: every method name the Python client invokes
  (``Client``/``Agent`` string literals passed to ``.invoke``);
- **implemented**: every method the in-process fake serves
  (``method == "..."`` dispatch comparisons in ``ChipStore.handle`` —
  the fake is the protocol's reference implementation, and the shared
  suite holds the C++ daemon to it);
- **documented**: every method row in ``doc/agent-protocol.md``'s
  Methods table (``| `name` | ...``).

Any one-sided name is drift: a client call the daemon will answer
METHOD_NOT_FOUND, an implemented-but-undocumented method the C++ side
will never learn about, or a documented method nobody serves.
"""

from __future__ import annotations

import ast
import re

from tools.oimlint.core import Finding, SourceTree

PASS_ID = "protocol-drift"
DESCRIPTION = "agent client / fake agent / doc method tables must agree"

CLIENT_FILES = ("oim_tpu/agent/agent.py", "oim_tpu/agent/client.py")
FAKE_FILE = "oim_tpu/agent/fake.py"
DOC_FILE = "doc/agent-protocol.md"

_DOC_ROW = re.compile(r"^\|\s*`([a-z_][a-z0-9_]*)`\s*\|")


def _tree_or_none(tree: SourceTree, rel: str):
    """A parsed module, or None when ``rel`` is absent from the scanned
    tree (fixture runs point the pass at a subset of the three files)."""
    try:
        return tree.tree(rel)
    except OSError:
        return None


def _invoked_methods(tree: SourceTree, files) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}
    for rel in files:
        mod = _tree_or_none(tree, rel)
        if mod is None:
            continue
        for node in ast.walk(mod):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "invoke"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.setdefault(node.args[0].value, (rel, node.lineno))
    return out


def _implemented_methods(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    """Names compared against a variable called ``method`` (the fake's
    dispatch convention)."""
    out: dict[str, tuple[str, int]] = {}
    mod = _tree_or_none(tree, rel)
    if mod is None:
        return out
    for node in ast.walk(mod):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        names = [
            s.id for s in sides if isinstance(s, ast.Name)
        ]
        if "method" not in names:
            continue
        for side in sides:
            if isinstance(side, ast.Constant) and isinstance(side.value, str):
                if re.fullmatch(r"[a-z_][a-z0-9_]*", side.value):
                    out.setdefault(side.value, (rel, side.lineno))
    return out


def _documented_methods(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}
    try:
        lines = tree.lines(rel)
    except OSError:
        return out
    for lineno, line in enumerate(lines, 1):
        m = _DOC_ROW.match(line.strip())
        if m:
            out.setdefault(m.group(1), (rel, lineno))
    return out


def run(
    tree: SourceTree,
    client_files=CLIENT_FILES,
    fake_file: str = FAKE_FILE,
    doc_file: str = DOC_FILE,
) -> list[Finding]:
    used = _invoked_methods(tree, client_files)
    implemented = _implemented_methods(tree, fake_file)
    documented = _documented_methods(tree, doc_file)
    findings: list[Finding] = []

    def drift(missing_from: str, have: dict, lack: dict, what: str) -> None:
        for name in sorted(set(have) - set(lack)):
            rel, line = have[name]
            findings.append(
                Finding(
                    PASS_ID,
                    rel,
                    line,
                    f"agent method {name!r} {what} but is missing from "
                    f"{missing_from}",
                )
            )

    drift(fake_file, used, implemented, "is invoked by the client")
    drift(doc_file, used, documented, "is invoked by the client")
    drift(doc_file, implemented, documented, "is served by the fake agent")
    drift(fake_file, documented, implemented, "is documented")
    return findings
