"""protocol-drift: client ↔ fake agent ↔ protocol doc agreement.

The reference kept its wire contract honest with a CI job that
extracted protobuf from ``spec.md`` and diffed it against ``oim.proto``
(reference Makefile:85-103).  The tpu-agent's JSON-RPC protocol has no
proto to diff, so this pass rebuilds the same gate from its three
sources of truth:

- **used**: every method name the Python client invokes
  (``Client``/``Agent`` string literals passed to ``.invoke``);
- **implemented**: every method the in-process fake serves
  (``method == "..."`` dispatch comparisons in ``ChipStore.handle`` —
  the fake is the protocol's reference implementation, and the shared
  suite holds the C++ daemon to it);
- **documented**: every method row in ``doc/agent-protocol.md``'s
  Methods table (``| `name` | ...``).

Any one-sided name is drift: a client call the daemon will answer
METHOD_NOT_FOUND, an implemented-but-undocumented method the C++ side
will never learn about, or a documented method nobody serves.

ISSUE 19 extends the same three-way gate to the serve plane's HTTP
wire surface, which had grown to five internal client families (router
proxy/splice, oimctl, checkpoint peer-load, disagg KV/slot ship,
autoscaler drain) with no drift check at all:

- **served**: route literals the serve-plane handlers dispatch on —
  string constants inside ``Compare`` nodes (``path == "/v1/kv"``,
  ``path in ("/v1/kv", "/v1/slot")``) in ``server.py``/``router.py``,
  plus ALL_CAPS module-level route tuples (the router's ``PROXIED``);
- **called**: route-shaped literals at client call sites — constants
  NOT inside a ``Compare`` (URL concatenation ``url + "/v1/generate"``,
  f-string fragments like ``f"{url}/v1/kv?rid=..."``, call arguments,
  route tuples), query strings stripped;
- **documented**: the ``| route | ... |`` table in ``doc/serving.md``.

A called route nobody serves 404s in production; an undocumented route
is invisible to operators; a documented route nobody serves is a
phantom row.  Served-but-never-internally-called is legal (the public
inference API's clients are external).
"""

from __future__ import annotations

import ast
import re

from tools.oimlint.core import Finding, SourceTree

PASS_ID = "protocol-drift"
DESCRIPTION = "agent client / fake agent / doc method tables must agree"

CLIENT_FILES = ("oim_tpu/agent/agent.py", "oim_tpu/agent/client.py")
FAKE_FILE = "oim_tpu/agent/fake.py"
DOC_FILE = "doc/agent-protocol.md"

_DOC_ROW = re.compile(r"^\|\s*`([a-z_][a-z0-9_]*)`\s*\|")

# -- HTTP wire surface (ISSUE 19) --------------------------------------------

HTTP_SERVED_FILES = (
    "oim_tpu/serve/server.py",
    "oim_tpu/serve/router.py",
)
HTTP_CLIENT_FILES = (
    "oim_tpu/serve/router.py",
    "oim_tpu/serve/disagg.py",
    "oim_tpu/cli/oimctl.py",
    "oim_tpu/checkpoint/manager.py",
    "oim_tpu/autoscale/autoscaler.py",
)
HTTP_DOC_FILE = "doc/serving.md"

# Route shape, anchored: the serve plane's URL namespace.  Anything
# else ("?", "/", log fragments) is not a route literal.
_ROUTE_RE = re.compile(
    r"^/(?:v1/[a-z_]+(?:/[a-z_]+)*|debugz(?:/[a-z_]+)?|healthz|metrics)$"
)
_HTTP_DOC_HEADER = re.compile(r"^\|\s*route\s*\|")
_HTTP_DOC_ROUTE = re.compile(r"`(/[^`\s]*)`")


def _tree_or_none(tree: SourceTree, rel: str):
    """A parsed module, or None when ``rel`` is absent from the scanned
    tree (fixture runs point the pass at a subset of the three files)."""
    try:
        return tree.tree(rel)
    except OSError:
        return None


def _invoked_methods(tree: SourceTree, files) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}
    for rel in files:
        mod = _tree_or_none(tree, rel)
        if mod is None:
            continue
        for node in ast.walk(mod):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "invoke"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.setdefault(node.args[0].value, (rel, node.lineno))
    return out


def _implemented_methods(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    """Names compared against a variable called ``method`` (the fake's
    dispatch convention)."""
    out: dict[str, tuple[str, int]] = {}
    mod = _tree_or_none(tree, rel)
    if mod is None:
        return out
    for node in ast.walk(mod):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        names = [
            s.id for s in sides if isinstance(s, ast.Name)
        ]
        if "method" not in names:
            continue
        for side in sides:
            if isinstance(side, ast.Constant) and isinstance(side.value, str):
                if re.fullmatch(r"[a-z_][a-z0-9_]*", side.value):
                    out.setdefault(side.value, (rel, side.lineno))
    return out


def _documented_methods(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}
    try:
        lines = tree.lines(rel)
    except OSError:
        return out
    for lineno, line in enumerate(lines, 1):
        m = _DOC_ROW.match(line.strip())
        if m:
            out.setdefault(m.group(1), (rel, lineno))
    return out


def _route(value: str) -> str | None:
    """The route a string literal names, query-stripped, or None when
    the literal is not route-shaped."""
    if not value.startswith("/"):
        return None
    path = value.split("?", 1)[0]
    return path if _ROUTE_RE.fullmatch(path) else None


def served_routes(tree: SourceTree, files) -> dict[str, tuple[str, int]]:
    """Routes the handlers dispatch on: Compare-side literals (either
    bare or inside membership tuples) plus ALL_CAPS module-level route
    tuples like the router's ``PROXIED``."""
    out: dict[str, tuple[str, int]] = {}
    for rel in files:
        mod = _tree_or_none(tree, rel)
        if mod is None:
            continue
        for node in ast.walk(mod):
            if isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    for c in ast.walk(side):
                        if isinstance(c, ast.Constant) and isinstance(c.value, str):
                            route = _route(c.value)
                            if route:
                                out.setdefault(route, (rel, c.lineno))
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                names = [t.id for t in node.targets if isinstance(t, ast.Name)]
                if not any(n.isupper() for n in names):
                    continue
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        route = _route(elt.value)
                        if route:
                            out.setdefault(route, (rel, elt.lineno))
    return out


def called_routes(tree: SourceTree, files) -> dict[str, tuple[str, int]]:
    """Routes at client call sites: every route-shaped string literal
    NOT inside a Compare — URL concatenation operands, f-string
    fragments (query-stripped), call args, route tuples."""
    out: dict[str, tuple[str, int]] = {}
    for rel in files:
        mod = _tree_or_none(tree, rel)
        if mod is None:
            continue
        in_compare: set[int] = set()
        for node in ast.walk(mod):
            if isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    for c in ast.walk(side):
                        in_compare.add(id(c))
        for node in ast.walk(mod):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in in_compare
            ):
                route = _route(node.value)
                if route:
                    out.setdefault(route, (rel, node.lineno))
    return out


def documented_routes(tree: SourceTree, rel: str) -> dict[str, tuple[str, int]]:
    """First-column backticked routes of the ``| route | ... |`` table
    (only that table; the doc has other tables)."""
    out: dict[str, tuple[str, int]] = {}
    try:
        lines = tree.lines(rel)
    except OSError:
        return out
    in_table = False
    for lineno, line in enumerate(lines, 1):
        stripped = line.strip()
        if _HTTP_DOC_HEADER.match(stripped) and "`" not in stripped.split("|")[1]:
            in_table = True
            continue
        if not in_table:
            continue
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = stripped.split("|")
        if len(cells) < 2 or set(cells[1].strip()) <= {"-", " "}:
            continue  # the |---|---| separator row
        for raw in _HTTP_DOC_ROUTE.findall(cells[1]):
            route = _route(raw)
            if route:
                out.setdefault(route, (rel, lineno))
    return out


def run(
    tree: SourceTree,
    client_files=CLIENT_FILES,
    fake_file: str = FAKE_FILE,
    doc_file: str = DOC_FILE,
    http_served_files=HTTP_SERVED_FILES,
    http_client_files=HTTP_CLIENT_FILES,
    http_doc_file: str = HTTP_DOC_FILE,
) -> list[Finding]:
    used = _invoked_methods(tree, client_files)
    implemented = _implemented_methods(tree, fake_file)
    documented = _documented_methods(tree, doc_file)
    findings: list[Finding] = []

    def drift(missing_from: str, have: dict, lack: dict, what: str) -> None:
        for name in sorted(set(have) - set(lack)):
            rel, line = have[name]
            findings.append(
                Finding(
                    PASS_ID,
                    rel,
                    line,
                    f"agent method {name!r} {what} but is missing from "
                    f"{missing_from}",
                )
            )

    drift(fake_file, used, implemented, "is invoked by the client")
    drift(doc_file, used, documented, "is invoked by the client")
    drift(doc_file, implemented, documented, "is served by the fake agent")
    drift(fake_file, documented, implemented, "is documented")

    # -- HTTP wire surface (ISSUE 19) ------------------------------------
    served = served_routes(tree, http_served_files)
    called = called_routes(tree, http_client_files)
    doc_routes = documented_routes(tree, http_doc_file)
    if served or called or doc_routes:
        for route in sorted(set(called) - set(served)):
            rel, line = called[route]
            findings.append(
                Finding(
                    PASS_ID, rel, line,
                    f"HTTP route {route!r} is called by an internal client "
                    f"but no serve-plane handler dispatches on it",
                )
            )
        for route in sorted((set(served) | set(called)) - set(doc_routes)):
            rel, line = served.get(route) or called[route]
            findings.append(
                Finding(
                    PASS_ID, rel, line,
                    f"HTTP route {route!r} is on the wire but missing from "
                    f"the {http_doc_file} route table",
                )
            )
        for route in sorted(set(doc_routes) - set(served)):
            rel, line = doc_routes[route]
            findings.append(
                Finding(
                    PASS_ID, rel, line,
                    f"HTTP route {route!r} is documented but no handler "
                    f"serves it (phantom row)",
                )
            )
    return findings
