"""``python -m tools.oimlint`` entry point (also ``make lint``)."""

import os
import sys

# Runnable from anywhere: the repo root is two levels up.
sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

from tools.oimlint.runner import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
