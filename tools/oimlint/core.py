"""oimvet core: Finding, source-tree cache, waivers, baseline.

The framework half of ``tools/oimlint`` (the passes live under
``tools/oimlint/passes``).  Design mirrors the reference's compiler-grade
CI gates (``go vet`` + the spec↔proto drift diff, reference
Makefile:85-103) translated to a Python control plane:

- every pass walks the **AST** (plus two documents: the agent protocol
  spec and the authz grant table), so the gate needs no accelerators, no
  network, and finishes well inside the 30 s ``make lint`` budget;
- findings are suppressed either **in code** (a
  ``# oimlint: disable=<pass>`` comment on the offending line or the
  line above — for violations that are *intentional and documented*) or
  **in the checked-in baseline** (``tools/oimlint/baseline.txt`` — for
  grandfathered findings that should be burned down over time).  The
  gate fails only on findings that are in neither set, so it can be
  adopted on an imperfect tree and still catch every NEW violation.

Baseline keys deliberately omit line numbers: an unrelated edit that
shifts a grandfathered finding must not break the gate.  The message
text (which names the class/attribute/method/pattern involved) is the
stable identity.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ERROR = "error"
WARNING = "warning"

_WAIVER_RE = re.compile(r"#\s*oimlint:\s*disable=([\w\-, ]+)")


@dataclass(frozen=True)
class Finding:
    """One violation: ``file:line``, the pass that found it, a message."""

    pass_id: str
    file: str  # repo-relative, '/'-separated
    line: int
    message: str
    severity: str = ERROR

    def key(self) -> str:
        """Line-number-free identity used for baseline matching."""
        return f"{self.pass_id} {self.file}: {self.message}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.pass_id}] {self.message}"


@dataclass
class SourceTree:
    """Parsed-AST cache over the scanned tree.

    ``roots`` are repo-relative directories walked for ``*.py`` (the
    generated proto bindings under ``spec/gen`` are skipped); any other
    repo-relative file (docs, fixtures) is reachable through
    :meth:`text` / :meth:`tree` on demand, which is how the
    protocol-drift pass reads ``doc/agent-protocol.md`` and how tests
    point passes at fixture snippets.
    """

    repo: str = REPO
    roots: tuple[str, ...] = ("oim_tpu",)
    _files: list[str] | None = None
    _sources: dict = field(default_factory=dict)
    _trees: dict = field(default_factory=dict)
    parse_errors: list[Finding] = field(default_factory=list)

    def files(self) -> list[str]:
        if self._files is None:
            out = []
            for root in self.roots:
                base = os.path.join(self.repo, root)
                for dirpath, dirnames, filenames in os.walk(base):
                    dirnames[:] = [
                        d for d in sorted(dirnames)
                        if d not in ("__pycache__", "gen")
                    ]
                    for name in sorted(filenames):
                        if name.endswith(".py"):
                            rel = os.path.relpath(
                                os.path.join(dirpath, name), self.repo
                            )
                            out.append(rel.replace(os.sep, "/"))
            self._files = out
        return self._files

    def text(self, rel: str) -> str:
        if rel not in self._sources:
            with open(os.path.join(self.repo, rel)) as f:
                self._sources[rel] = f.read()
        return self._sources[rel]

    def lines(self, rel: str) -> list[str]:
        return self.text(rel).splitlines()

    def tree(self, rel: str) -> ast.Module | None:
        if rel not in self._trees:
            try:
                self._trees[rel] = ast.parse(self.text(rel), filename=rel)
            except SyntaxError as exc:
                self._trees[rel] = None
                self.parse_errors.append(
                    Finding("parse", rel, exc.lineno or 0, f"unparseable: {exc}")
                )
        return self._trees[rel]


def waived_passes(tree: SourceTree, rel: str, line: int) -> set[str]:
    """Pass ids disabled at ``rel:line`` by a waiver comment on that line
    or the line above (``# oimlint: disable=pass-a,pass-b`` / ``=all``)."""
    out: set[str] = set()
    lines = tree.lines(rel)
    for idx in (line - 1, line - 2):  # the line itself, then the one above
        if 0 <= idx < len(lines):
            m = _WAIVER_RE.search(lines[idx])
            if m:
                out.update(p.strip() for p in m.group(1).split(","))
    return out


def apply_waivers(
    tree: SourceTree, findings: list[Finding]
) -> tuple[list[Finding], list[Finding]]:
    """Split findings into (kept, waived) by in-code waiver comments."""
    kept: list[Finding] = []
    waived: list[Finding] = []
    for finding in findings:
        try:
            disabled = waived_passes(tree, finding.file, finding.line)
        except OSError:
            disabled = set()
        if finding.pass_id in disabled or "all" in disabled:
            waived.append(finding)
        else:
            kept.append(finding)
    return kept, waived


DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.txt")


def load_baseline(path: str) -> set[str]:
    """Baseline keys from ``path``; a missing file is an empty baseline."""
    try:
        with open(path) as f:
            return {
                line.strip()
                for line in f
                if line.strip() and not line.lstrip().startswith("#")
            }
    except FileNotFoundError:
        return set()


def write_baseline(path: str, findings: list[Finding]) -> None:
    keys = sorted({f.key() for f in findings})
    with open(path, "w") as f:
        f.write(
            "# oimlint baseline: grandfathered findings (the gate fails only\n"
            "# on findings NOT listed here).  Regenerate with\n"
            "#   python -m tools.oimlint --update-baseline\n"
            "# after fixing entries; never add new violations here without\n"
            "# a review — prefer fixing, or an in-code waiver comment with\n"
            "# a justification.  Keys are line-number-free on purpose.\n"
        )
        for key in keys:
            f.write(key + "\n")


# -- shared AST helpers ------------------------------------------------------


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    """Dotted name of a call's callee (``threading.Thread`` for
    ``threading.Thread(...)``), else None."""
    return dotted(node.func)


def keyword_arg(node: ast.Call, name: str) -> ast.expr | None:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def class_methods(cls: ast.ClassDef) -> dict[str, ast.FunctionDef]:
    return {
        node.name: node
        for node in cls.body
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def module_classes(tree: ast.Module):
    """Top-level classes plus classes nested in top-level functions/classes
    (the fake agent defines handler classes inside ``__init__``)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node
