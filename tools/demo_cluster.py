#!/usr/bin/env python3
"""Interactive demo cluster: the whole control plane on one machine.

≙ reference test/start-stop.make — ``make start`` brings up the demo stack
(there: SPDK vhost + registry + controller + proxied driver; here:
tpu-agent in fake-chip mode + mTLS registry + controller + CSI driver in
remote mode), ``make stop`` tears it down, ``make demo`` runs the
README-style volume round trip (≙ reference README.md:432-449 Malloc
demo).

State lives under ``_work/demo`` (CA tree, sockets, pidfile, logs), like
the reference's ``_work``.

Usage:  python tools/demo_cluster.py start|stop|status|demo|demo-serve
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORK = os.path.join(REPO, "_work", "demo")
PIDFILE = os.path.join(WORK, "pids.json")
CA_DIR = os.path.join(WORK, "ca")
REGISTRY_ENDPOINT = "tcp://127.0.0.1:8970"
CONTROLLER_ENDPOINT = "tcp://127.0.0.1:8971"
CONTROLLER_ID = "demo-host"
AGENT_SOCKET = os.path.join(WORK, "tpu-agent.sock")
CSI_SOCKET = os.path.join(WORK, "csi.sock")
NATIVE_AGENT = os.path.join(REPO, "native", "tpu-agent", "tpu-agent")

ENV = dict(os.environ, PYTHONPATH=REPO)


def _spawn(args: list[str], name: str, pids: dict[str, int]) -> int:
    log_path = os.path.join(WORK, f"{name}.log")
    with open(log_path, "w") as logf:
        proc = subprocess.Popen(
            args, stdout=logf, stderr=subprocess.STDOUT, env=ENV,
            start_new_session=True,
        )
    print(f"  {name}: pid {proc.pid} (log {os.path.relpath(log_path, REPO)})")
    # The pidfile is written after EVERY spawn, not once at the end: if a
    # later daemon's socket never appears and start() raises, stop() can
    # still find and kill what already came up (otherwise a failed start
    # orphans JAX-preloading daemons — the round-1 wedged-TPU scenario).
    pids[name] = proc.pid
    with open(PIDFILE, "w") as f:
        json.dump(pids, f)
    return proc.pid


def _wait_file(path: str, timeout: float = 15.0) -> None:
    deadline = time.time() + timeout
    while not os.path.exists(path):
        if time.time() > deadline:
            raise SystemExit(f"{path} never appeared — check logs in {WORK}")
        time.sleep(0.05)


def _tls_args(cn: str) -> list[str]:
    return [
        "--ca", f"{CA_DIR}/ca.crt",
        "--cert", f"{CA_DIR}/{cn}.crt",
        "--key", f"{CA_DIR}/{cn}.key",
    ]


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _load_pids() -> dict[str, int]:
    if not os.path.exists(PIDFILE):
        return {}
    with open(PIDFILE) as f:
        return json.load(f)


def start() -> None:
    if any(_alive(p) for p in _load_pids().values()):
        raise SystemExit("demo cluster already running — `stop` first")
    os.makedirs(WORK, exist_ok=True)
    try:
        _start_daemons()
    except BaseException:
        # A failed bring-up must kill whatever it already spawned — past
        # the already-running check above, every pidfile entry is ours
        # (written incrementally by _spawn), so stop() cannot hit a
        # pre-existing cluster.
        _stop_if_running()
        raise


def _start_daemons() -> None:

    from oim_tpu.common.ca import CertAuthority

    if not os.path.exists(f"{CA_DIR}/ca.crt"):
        CertAuthority().write_tree(
            CA_DIR,
            [
                "component.registry",
                f"controller.{CONTROLLER_ID}",
                f"host.{CONTROLLER_ID}",
                "user.admin",
            ],
        )
        print(f"  CA tree: {os.path.relpath(CA_DIR, REPO)}")

    pids = {}
    if os.path.exists(NATIVE_AGENT):
        _spawn(
            [NATIVE_AGENT, "--socket", AGENT_SOCKET,
             "--fake-chips", "8", "--mesh", "2x2x2",
             "--state-dir", os.path.join(WORK, "dev")],
            "tpu-agent", pids,
        )
    else:
        print("  (native agent not built; using the Python fake)")
        _spawn(
            [sys.executable, "-m", "oim_tpu.cli.agent_main",
             "--socket", AGENT_SOCKET, "--fake-chips", "8", "--mesh", "2x2x2",
             "--state-dir", os.path.join(WORK, "dev")],
            "tpu-agent", pids,
        )
    _wait_file(AGENT_SOCKET)

    _spawn(
        [sys.executable, "-m", "oim_tpu.cli.registry_main",
         "--endpoint", REGISTRY_ENDPOINT,
         "--db", os.path.join(WORK, "registry.db"),
         *_tls_args("component.registry")],
        "oim-registry", pids,
    )
    _spawn(
        [sys.executable, "-m", "oim_tpu.cli.controller_main",
         "--id", CONTROLLER_ID,
         "--endpoint", CONTROLLER_ENDPOINT,
         "--agent-socket", AGENT_SOCKET,
         "--registry", REGISTRY_ENDPOINT,
         "--registry-delay", "10",
         *_tls_args(f"controller.{CONTROLLER_ID}")],
        "oim-controller", pids,
    )
    _spawn(
        [sys.executable, "-m", "oim_tpu.cli.csi_main",
         "--endpoint", f"unix://{CSI_SOCKET}",
         "--node-id", "demo-node",
         "--registry", REGISTRY_ENDPOINT,
         "--controller-id", CONTROLLER_ID,
         *_tls_args(f"host.{CONTROLLER_ID}")],
        "oim-csi-driver", pids,
    )
    _wait_file(CSI_SOCKET)
    print(f"""
demo cluster up.  Try:
  python -m oim_tpu.cli.oimctl --registry {REGISTRY_ENDPOINT} \\
      --ca {CA_DIR}/ca.crt --cert {CA_DIR}/user.admin.crt \\
      --key {CA_DIR}/user.admin.key -get ""
  python tools/demo_cluster.py demo     # full volume round trip
  python tools/demo_cluster.py stop
""")


def stop() -> None:
    pids = _load_pids()
    if not pids:
        print("nothing to stop")
        return
    for name, pid in pids.items():
        if _alive(pid):
            print(f"  stopping {name} (pid {pid})")
            try:
                os.killpg(pid, signal.SIGTERM)
            except OSError:
                os.kill(pid, signal.SIGTERM)
    deadline = time.time() + 5
    while time.time() < deadline and any(_alive(p) for p in pids.values()):
        time.sleep(0.1)
    for name, pid in pids.items():
        if _alive(pid):
            print(f"  killing {name}")
            try:
                os.killpg(pid, signal.SIGKILL)
            except OSError:
                os.kill(pid, signal.SIGKILL)
    os.unlink(PIDFILE)
    for sock in (AGENT_SOCKET, CSI_SOCKET):
        if os.path.exists(sock):
            os.unlink(sock)
    print("stopped")


def status() -> int:
    pids = _load_pids()
    if not pids:
        print("demo cluster: not running")
        return 1
    down = 0
    for name, pid in pids.items():
        state = "up" if _alive(pid) else "DOWN"
        down += state == "DOWN"
        print(f"  {name:16s} pid {pid:<8d} {state}")
    return 1 if down else 0


def demo() -> None:
    """CreateVolume → NodeStage → NodePublish → inspect → teardown, over
    the real sockets (≙ reference README.md:432-449).

    If the cluster is not already up, it is started for the demo and
    stopped afterwards — even on failure.  A demo run must never leave
    daemons behind: on this box a leaked JAX-preloaded process wedges the
    single TPU for every later user (round-1 postmortem; the reference's
    fixture kills its daemon's process group for the same reason,
    test/pkg/spdk/spdk.go:84-278).
    """
    started_here = status() != 0
    if started_here:
        print("cluster not running — starting it for the demo")
        import atexit

        start()
        # Registered only after start() succeeded: a partially-up cluster
        # makes start() raise "already running", and tearing down the
        # user's surviving daemons from atexit would destroy state they
        # were likely inspecting.  Belt and braces from here on:
        # ``finally`` covers exceptions, atexit covers SIGPIPE/interpreter
        # teardown paths that skip it.
        atexit.register(_stop_if_running)
        try:
            _wait_file(CSI_SOCKET, timeout=20)
            import grpc

            # The controller may not have self-registered yet (each daemon
            # cold-starts a JAX-preloading interpreter); every RPC in the
            # round trip is idempotent, so retry — but only on the status
            # codes the registration race actually produces.
            retryable = (
                grpc.StatusCode.UNAVAILABLE,
                grpc.StatusCode.NOT_FOUND,
                grpc.StatusCode.FAILED_PRECONDITION,
            )
            deadline = time.time() + 60
            while True:
                try:
                    _demo_roundtrip()
                    break
                except grpc.RpcError as err:
                    if err.code() not in retryable or time.time() > deadline:
                        raise
                    time.sleep(0.5)
        finally:
            _stop_if_running()
        return
    _demo_roundtrip()


def _stop_if_running() -> None:
    if any(_alive(p) for p in _load_pids().values()):
        stop()


def _demo_roundtrip() -> None:
    import grpc

    from oim_tpu.spec import CSI_CONTROLLER, CSI_NODE, csi_pb2

    channel = grpc.insecure_channel(f"unix://{CSI_SOCKET}")
    cap = csi_pb2.VolumeCapability()
    cap.mount.SetInParent()
    cap.access_mode.mode = csi_pb2.VolumeCapability.AccessMode.SINGLE_NODE_WRITER

    print("CreateVolume pvc-demo (4 chips)...")
    vol = CSI_CONTROLLER.stub(channel).CreateVolume(
        csi_pb2.CreateVolumeRequest(
            name="pvc-demo",
            volume_capabilities=[cap],
            parameters={"chipCount": "4"},
        ),
        timeout=30,
    ).volume
    staging = os.path.join(WORK, "staging")
    target = os.path.join(WORK, "pod", "tpu")
    node = CSI_NODE.stub(channel)
    print("NodeStageVolume...")
    node.NodeStageVolume(
        csi_pb2.NodeStageVolumeRequest(
            volume_id="pvc-demo",
            staging_target_path=staging,
            volume_capability=cap,
            volume_context=dict(vol.volume_context),
        ),
        timeout=30,
    )
    print("NodePublishVolume...")
    node.NodePublishVolume(
        csi_pb2.NodePublishVolumeRequest(
            volume_id="pvc-demo",
            staging_target_path=staging,
            target_path=target,
            volume_capability=cap,
        ),
        timeout=30,
    )
    with open(os.path.join(target, "tpu-bootstrap.json")) as f:
        bootstrap = json.load(f)
    print("staged bootstrap:")
    print(json.dumps(bootstrap, indent=2))
    print("teardown...")
    node.NodeUnpublishVolume(
        csi_pb2.NodeUnpublishVolumeRequest(
            volume_id="pvc-demo", target_path=target
        ),
        timeout=30,
    )
    node.NodeUnstageVolume(
        csi_pb2.NodeUnstageVolumeRequest(
            volume_id="pvc-demo", staging_target_path=staging
        ),
        timeout=30,
    )
    CSI_CONTROLLER.stub(channel).DeleteVolume(
        csi_pb2.DeleteVolumeRequest(volume_id="pvc-demo"), timeout=30
    )
    print("demo round trip OK")


def demo_serve() -> None:
    """The serving data plane, end to end on one machine: two tiny
    oim-serve instances (CPU, ~15 s warmup each) behind oim-route, one
    routed generation via oimctl, teardown.  Self-contained like
    ``demo`` — never leaves daemons behind."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import urllib.request

    import procutil

    env = dict(
        ENV, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu"
    )  # a demo must not squat the real chip
    model = [
        "--vocab-size", "101", "--d-model", "32", "--n-layers", "2",
        "--n-heads", "4", "--d-ff", "64", "--dtype", "float32",
        "--max-len", "64", "--n-slots", "2", "--chunk", "4",
    ]
    os.makedirs(WORK, exist_ok=True)
    procs = []

    def spawn_py(name, argv):
        logf = open(os.path.join(WORK, f"{name}.log"), "w")
        return procutil.spawn(
            [sys.executable, "-m", argv[0], *argv[1:]],
            env=env, stdout=logf, stderr=logf,
        )

    try:
        a = spawn_py("demo-serve-a", [
            "oim_tpu.cli.serve_main", *model, "--port", "8975"])
        b = spawn_py("demo-serve-b", [
            "oim_tpu.cli.serve_main", *model, "--port", "8976"])
        procs += [a, b]
        for proc, port in ((a, 8975), (b, 8976)):
            # A stale listener answering on the port would make the demo
            # proceed against the WRONG process; owning the port is part
            # of readiness.
            if proc.poll() is not None:
                raise RuntimeError(
                    f"serve :{port} exited at startup (rc={proc.returncode}"
                    f"; port in use by a stale demo?) — see {WORK}"
                )
            deadline = time.time() + 90
            while True:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=2
                    ):
                        break
                except OSError:
                    if proc.poll() is not None:
                        raise RuntimeError(
                            f"serve :{port} died during warmup "
                            f"(rc={proc.returncode}) — see {WORK}"
                        )
                    if time.time() > deadline:
                        raise RuntimeError(f"serve :{port} never came up")
                    time.sleep(0.5)
            print(f"oim-serve :{port} healthy")
        router = spawn_py("demo-route", [
            "oim_tpu.cli.route_main",
            "--backend", "http://127.0.0.1:8975",
            "--backend", "http://127.0.0.1:8976",
            "--port", "8977", "--health-interval", "1"])
        procs.append(router)
        deadline = time.time() + 30
        while True:
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:8977/healthz", timeout=2
                ) as r:
                    if json.loads(r.read())["healthy_backends"] == 2:
                        break
            except OSError:
                pass
            if router.poll() is not None:
                raise RuntimeError(
                    f"router exited (rc={router.returncode}) — see {WORK}"
                )
            if time.time() > deadline:
                raise RuntimeError("router never saw both backends")
            time.sleep(0.5)
        print("oim-route :8977 balancing 2 backends")
        out = subprocess.run(
            [sys.executable, "-m", "oim_tpu.cli.oimctl", "generate",
             "1", "2", "3", "--serve", "http://127.0.0.1:8977",
             "--max-new-tokens", "8"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        if out.returncode != 0:
            raise RuntimeError(f"oimctl generate failed: {out.stderr[-500:]}")
        print("routed generation:", out.stdout.strip())
        print("serving demo OK")
    finally:
        procutil.stop_all(procs)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    commands = ("start", "stop", "status", "demo", "demo-serve")
    if len(argv) != 1 or argv[0] not in commands:
        print(__doc__)
        return 2
    if argv[0] == "start":
        start()
    elif argv[0] == "stop":
        stop()
    elif argv[0] == "status":
        return status()
    elif argv[0] == "demo-serve":
        demo_serve()
    else:
        demo()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
