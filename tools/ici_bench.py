#!/usr/bin/env python3
"""ICI all-reduce benchmark CLI (BASELINE.md metric 2).

Run directly on whatever ``jax.devices()`` offers, or against a
CSI-provisioned slice by pointing ``--bootstrap`` at the staged
``tpu-bootstrap.json`` (config 3 in BASELINE.json: the slice the control
plane handed out is what gets measured).  Emits a perfdash-framed PerfData
block (≙ reference test/e2e/perftype).

Examples:
    # CPU plumbing check (8 virtual devices):
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/ici_bench.py --sizes-mb 1 4

    # On a CSI-provisioned slice, inside the pod:
    python tools/ici_bench.py --bootstrap /tpu/tpu-bootstrap.json \
        --line-rate 90
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--sizes-mb", type=float, nargs="+", default=[1, 4, 16, 64]
    )
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument(
        "--line-rate",
        type=float,
        default=0.0,
        help="per-direction ICI link rate in GB/s; adds the BusBwFraction "
        "bucket for the >=90%% target",
    )
    parser.add_argument(
        "--ops", nargs="+", default=["all_reduce"],
        choices=["all_reduce", "all_gather", "reduce_scatter",
                 "all_to_all", "all"],
        help="collectives to measure ('all' = the whole matrix)",
    )
    parser.add_argument(
        "--bootstrap",
        default="",
        help="path to a CSI-staged tpu-bootstrap.json; joins the slice's "
        "process group before benchmarking",
    )
    args = parser.parse_args(argv)

    if args.bootstrap:
        from oim_tpu.parallel.coordinator import (
            initialize_distributed,
            load_bootstrap,
        )

        initialize_distributed(load_bootstrap(args.bootstrap))

    from oim_tpu.bench import COLLECTIVES, collective_bench

    ops = tuple(COLLECTIVES) if args.ops == ["all"] else tuple(args.ops)
    perf = collective_bench(
        ops=ops,
        sizes_mb=tuple(args.sizes_mb),
        dtype=args.dtype,
        iters=args.iters,
        warmup=args.warmup,
        line_rate_gbps=args.line_rate,
    )
    print(perf.render())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
