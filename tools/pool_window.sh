#!/usr/bin/env bash
# Pool-window playbook: the moment the axon TPU pool answers, capture
# every on-chip number the round needs, in priority order, and commit
# the evidence after each stage — windows can close mid-run (BASELINE
# pool-status notes; round-3 lost the fused-CE number to exactly that).
#
#   ./tools/pool_window.sh            probe once; run the playbook if up
#   ./tools/pool_window.sh --loop     probe every ~17 min until a window
#
# Stages (each is independently useful evidence):
#   1. bench.py      — train/MFU + fused CE first (reordered), then
#                      decode, serving (+ repeats for the swing), spec
#                      margin check, MoE, flash, chip-binding tier.
#                      Auto-writes BENCH_LAST_GOOD.json + history.
#   2. bench.py #2   — the >=2-same-code-runs requirement for every
#                      headline row (VERDICT r3 weak #1/#2).
#   3. real tiers    — TEST_REAL_TPU (binding) + TEST_REAL_PJRT_CLIENT
#                      (agent on the live plugin), serialized with the
#                      chip.
#   4. GQA matrix    — tools/decode_bench.py --record appends to history.
set -u
cd "$(dirname "$0")/.."

probe() {
    timeout 70 python - <<'EOF'
import subprocess, sys
try:
    r = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"], timeout=60,
        capture_output=True,
    )
    sys.exit(r.returncode)
except subprocess.TimeoutExpired:
    sys.exit(3)
EOF
}

commit_evidence() {
    git add BENCH_LAST_GOOD.json BENCH_HISTORY.jsonl 2>/dev/null
    git diff --cached --quiet 2>/dev/null || git commit -m "$1"
}

run_window() {
    echo "=== pool window open: $(date -u) ==="
    echo "--- stage 1: bench run A"
    python bench.py; rc=$?
    commit_evidence "On-chip evidence: bench run A ($(date -u +%H:%MZ))"
    [ $rc -ne 0 ] && echo "bench A failed rc=$rc (continuing)"

    echo "--- stage 2: bench run B (same code)"
    python bench.py
    commit_evidence "On-chip evidence: bench run B, same code ($(date -u +%H:%MZ))"

    echo "--- stage 3: real-device tiers"
    TEST_REAL_PJRT_CLIENT=1 python -m pytest \
        tests/test_pjrt_loader.py -q -k real || true
    TEST_REAL_TPU=1 python -m pytest tests/test_real_tpu.py -q || true

    echo "--- stage 4: GQA decode matrix"
    python tools/decode_bench.py --iters 6 --record || true
    commit_evidence "On-chip evidence: GQA decode matrix ($(date -u +%H:%MZ))"

    echo "--- stage 5: int4-weights decode matrix (round-5 lever)"
    python tools/decode_bench.py --iters 6 --record --weights-int4 || true
    commit_evidence "On-chip evidence: int4 decode matrix ($(date -u +%H:%MZ))"
    echo "=== window playbook complete: $(date -u) ==="
}

if [ "${1:-}" = "--loop" ]; then
    while true; do
        if probe; then
            run_window
            exit 0
        fi
        echo "pool down ($(date -u +%H:%M:%SZ)); next probe in ~17 min"
        sleep 1020
    done
else
    if probe; then
        run_window
    else
        echo "pool down"
        exit 3
    fi
fi
