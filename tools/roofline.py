#!/usr/bin/env python3
"""Flagship-MFU roofline: where the train-step time goes and what bounds it.

Answers the round-2 verdict's #9: with the headline MFU measured, attribute
the remaining gap to components and state what bounds the number for this
geometry.  Two inputs:

1. **Analytic executed-FLOPs split** per token (embedding gather executes
   ~0 matmul FLOPs and is excluded — note this is NOT the 6·N convention
   bench.py reports as the headline, which counts every parameter; both
   are printed so the two MFU flavors are explicit).
2. **Measured ablations** on the real chip: the full step vs variants with
   one component shrunk (tiny vocab → no unembed; tiny d_ff → no MLP;
   short sequence at equal token count → no attention-score term), plus
   the pure-matmul practical ceiling (big bf16 matmul, the most MXU-
   friendly op XLA will ever see here).

Usage: python tools/roofline.py  (prints a table; add --json for raw)
"""

import argparse
import json
import os
import sys
import time
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def analytic_split(cfg, seq: int) -> dict:
    """Executed matmul FLOPs per token, fwd+bwd (bwd = 2x fwd), by part."""
    d, L, V, ff = cfg.d_model, cfg.n_layers, cfg.vocab_size, cfg.d_ff
    fwd = {
        "attn_proj": L * 8 * d * d,          # q,k,v,o: 4 matmuls x 2d^2
        # qk^T + pv: the causal flash kernel skips fully-masked blocks,
        # so each matmul executes ~T/2 of the T positions per token:
        # 2 matmuls x 2 FLOPs/MAC x (T/2)·d = 2·T·d.
        "attn_scores": L * 2 * seq * d,
        "mlp": L * 6 * d * ff,               # SwiGLU: gate, up, down matmuls
        "unembed": 2 * d * V,
    }
    return {k: 3 * v for k, v in fwd.items()}  # train = fwd + 2x-fwd bwd


def _measure_step(cfg, batch, seq, n_iter, rtt_s) -> float:
    """Seconds per train step — bench.py's ONE timing harness (scan-fused,
    readback-ended, rtt-subtracted), fed a fresh model for this cfg."""
    import jax

    import bench
    from oim_tpu.models import init_params

    params = init_params(jax.random.PRNGKey(0), cfg)
    return bench.measure_train_step(cfg, params, batch, seq, n_iter, rtt_s)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--iters", type=int, default=20)
    args = parser.parse_args(argv)

    import bench

    # Probe the accelerator in a SUBPROCESS before any in-process jax
    # touch (bench.py's discipline): a wedged pool would otherwise hang
    # this process at the first device op with no timeout possible.
    if not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        if not bench.probe_backend(
            float(os.environ.get("OIM_BENCH_PROBE_DEADLINE", "120"))
        ):
            print(
                json.dumps({"error": "tpu_unavailable", "hint":
                            "pool down or wedged; roofline needs the "
                            "real chip — rerun when the probe passes"})
            )
            return 1

    import jax
    import jax.numpy as jnp

    on_tpu = jax.default_backend() not in ("cpu",)
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    peak = bench.PEAK_TFLOPS.get(gen, 0.0) if on_tpu else 0.0

    # Tunnel rtt (one scalar readback) to subtract from timed regions —
    # median of 5: single samples on the tunnel jitter by tens of ms,
    # which would swing every derived number.
    import statistics

    x = jnp.zeros((), jnp.float32) + 1
    float(x)
    samples = []
    for i in range(5):
        t0 = time.perf_counter()
        float(x + i)
        samples.append(time.perf_counter() - t0)
    rtt_s = statistics.median(samples)

    cfg, batch, seq = bench._flagship_cfg(on_tpu)
    n_iter = args.iters if on_tpu else 2
    toks = batch * seq

    # Practical MXU ceiling: the biggest friendliest bf16 matmul.
    n = 8192 if on_tpu else 256
    a = jnp.ones((n, n), jnp.bfloat16)

    mm_iters = 50 if on_tpu else 4  # long enough to dwarf rtt jitter

    @jax.jit
    def mm_loop(a):
        def body(c, _):
            c = (c @ a) * (1.0 / n)
            return c, c[0, 0]
        return jax.lax.scan(body, a, None, length=mm_iters)[1][-1]

    float(mm_loop(a))  # compile
    t0 = time.perf_counter()
    float(mm_loop(a))
    mm_dt = (time.perf_counter() - t0 - rtt_s) / mm_iters
    mm_tf = 2 * n**3 / mm_dt / 1e12

    full_dt = _measure_step(cfg, batch, seq, n_iter, rtt_s)

    ablations = {
        # vocab 512: unembed fwd+bwd and the CE softmax shrink to noise.
        "unembed+ce": replace(cfg, vocab_size=512),
        # d_ff 256: the MLP pair shrinks 16x.
        "mlp": replace(cfg, d_ff=256),
    }
    measured = {}
    for name, acfg in ablations.items():
        measured[name] = full_dt - _measure_step(acfg, batch, seq, n_iter, rtt_s)
    # Attention scores: same token count at seq 256 (batch x4) kills ~3/4
    # of the T-proportional score FLOPs while keeping every matmul size.
    short_dt = _measure_step(cfg, batch * 4, seq // 4, n_iter, rtt_s)
    measured["attn_scores(3/4)"] = full_dt - short_dt

    split = analytic_split(cfg, seq)
    exec_flops_tok = sum(split.values())
    import oim_tpu.models as m

    # eval_shape: sizes only, no device allocation (the measure steps
    # above already materialized five full models on the chip).  cfg is
    # closed over, not passed — eval_shape would trace it.
    shapes = jax.eval_shape(
        lambda key: m.init_params(key, cfg), jax.random.PRNGKey(0)
    )
    n_params = sum(p.size for p in jax.tree.leaves(shapes))
    six_n_tok = 6 * n_params + 12 * cfg.n_layers * seq * cfg.d_model

    out = {
        "gen": gen,
        "nominal_peak_tflops": peak,
        "matmul_ceiling_tflops": round(mm_tf, 1),
        "train_step_ms": round(full_dt * 1000, 2),
        "tok_per_s": round(toks / full_dt),
        "mfu_6n_pct": round(six_n_tok * toks / full_dt / (peak * 1e12) * 100, 1)
        if peak else None,
        "mfu_executed_pct": round(
            exec_flops_tok * toks / full_dt / (peak * 1e12) * 100, 1
        ) if peak else None,
        "mfu_vs_matmul_ceiling_pct": round(
            exec_flops_tok * toks / full_dt / (mm_tf * 1e12) * 100, 1
        ),
        "analytic_flops_share_pct": {
            k: round(100 * v / exec_flops_tok, 1) for k, v in split.items()
        },
        "measured_component_ms": {
            k: round(v * 1000, 2) for k, v in measured.items()
        },
        "tunnel_rtt_ms": round(rtt_s * 1000, 1),
    }
    print(json.dumps(out) if args.json else json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
