#!/usr/bin/env python3
"""Thin alias: the metrics lint is now oimlint's ``metrics`` pass.

Kept so existing workflows (``make lint-metrics``, scripts invoking
``tools/check_metrics.py`` directly) don't break; the implementation —
the same AST source scan plus runtime-registry check — lives in
``tools/oimlint/passes/metricspass.py`` so there is ONE analyzer (see
doc/development.md "The oimvet static analyzer").

Exit 1 with one line per violation; exit 0 otherwise — same contract
as before the fold.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.oimlint.runner import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main(["--passes", "metrics", "--quiet"]))
