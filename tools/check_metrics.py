#!/usr/bin/env python3
"""Metrics lint: every registered series must be ``oim_``-prefixed with
non-empty HELP.

Two passes, both fast and stdlib-only:

1. **Source scan** (AST): every ``.counter("name", "help", ...)`` /
   ``.gauge(...)`` / ``.histogram(...)`` call under ``oim_tpu/`` whose
   name is a string literal is checked — this catches instruments
   registered at instance-construction time, which a runtime import can
   never see.
2. **Runtime check**: import the always-importable metrics-defining
   modules (no jax required) and validate what actually landed in the
   process registry — this catches dynamically built names the AST pass
   skips.

Exit 1 with one line per violation; silent success otherwise.  Invoked
by ``make lint-metrics``.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "oim_tpu")

REGISTER_METHODS = {"counter", "gauge", "histogram"}


def scan_file(path: str) -> list[str]:
    with open(path) as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [f"{path}: unparseable: {exc}"]
    rel = os.path.relpath(path, REPO)
    problems: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr in REGISTER_METHODS):
            continue
        if not node.args:
            continue
        name_node = node.args[0]
        if not (isinstance(name_node, ast.Constant) and isinstance(name_node.value, str)):
            continue  # dynamic name: left to the runtime pass
        name = name_node.value
        where = f"{rel}:{node.lineno}"
        if not name.startswith("oim_"):
            problems.append(
                f"{where}: series {name!r} is not 'oim_'-prefixed"
            )
        help_node = node.args[1] if len(node.args) > 1 else None
        if isinstance(help_node, ast.Constant) and isinstance(help_node.value, str):
            if not help_node.value.strip():
                problems.append(f"{where}: series {name!r} has empty HELP")
        elif isinstance(help_node, ast.JoinedStr):
            pass  # f-string help: non-empty by construction
        elif help_node is None and "help_" not in {
            kw.arg for kw in node.keywords
        }:
            problems.append(f"{where}: series {name!r} has no HELP argument")
    return problems


def scan_sources() -> list[str]:
    problems: list[str] = []
    for root, _dirs, files in os.walk(PACKAGE):
        if os.path.basename(root) == "gen":
            continue  # generated proto bindings
        for name in sorted(files):
            if name.endswith(".py"):
                problems.extend(scan_file(os.path.join(root, name)))
    return problems


def check_runtime() -> list[str]:
    sys.path.insert(0, REPO)
    # The jax-free metrics definers; jax-importing modules (data,
    # checkpoint, serve engine) are covered by the source scan.
    import oim_tpu.common.events  # noqa: F401
    import oim_tpu.common.metrics as metrics
    import oim_tpu.common.resilience  # noqa: F401
    import oim_tpu.common.tracing  # noqa: F401

    problems: list[str] = []
    for name, metric in sorted(metrics.registry()._metrics.items()):
        if not name.startswith("oim_"):
            problems.append(f"runtime registry: series {name!r} not 'oim_'-prefixed")
        if not str(getattr(metric, "help", "")).strip():
            problems.append(f"runtime registry: series {name!r} has empty HELP")
    return problems


def main() -> int:
    problems = scan_sources() + check_runtime()
    for problem in problems:
        print(problem)
    if problems:
        print(f"lint-metrics: {len(problems)} problem(s)")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
