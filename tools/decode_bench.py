#!/usr/bin/env python3
"""Long-context decode throughput: GQA × KV-cache dtype matrix.

Decode is cache-bandwidth-bound (doc/compute.md), so its two levers are
kv-head count (GQA) and cache element width (int8 quantization,
ops/quant.py) — this tool measures the matrix on the real chip and
prints one line per cell.  Timing discipline per BASELINE.md: N
generations ride back-to-back dispatches, the clock stops on one
materializing readback, and the measured tunnel rtt is subtracted.

Usage (real TPU; ~2 min including compiles):
    python tools/decode_bench.py [--prompt 1024] [--new 128] [--batch 8]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_rtt(jnp):
    import statistics

    x = jnp.ones((128, 128), jnp.bfloat16)
    y = (x @ x).sum()
    float(y)
    rtts = []
    for i in range(5):
        done = ((x * (1.0 + i)) @ x).sum()
        time.sleep(0.3)
        t0 = time.perf_counter()
        float(done)
        rtts.append(time.perf_counter() - t0)
    return statistics.median(rtts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--prompt", type=int, default=1024)
    p.add_argument("--new", type=int, default=128)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--iters", type=int, default=4)
    # Flagship geometry by default; shrink for CPU smoke runs.
    p.add_argument("--vocab-size", type=int, default=32768)
    p.add_argument("--d-model", type=int, default=1024)
    p.add_argument("--n-layers", type=int, default=8)
    p.add_argument("--n-heads", type=int, default=16)
    p.add_argument("--d-ff", type=int, default=4096)
    p.add_argument("--dtype", default="bfloat16")
    quant = p.add_mutually_exclusive_group()
    quant.add_argument(
        "--weights-int8", action="store_true",
        help="also measure with weight-only int8 matmul weights",
    )
    quant.add_argument(
        "--weights-int4", action="store_true",
        help="also measure with weight-only int4 (group-wise scales)",
    )
    p.add_argument(
        "--record", action="store_true",
        help="append the result matrix to BENCH_HISTORY.jsonl "
             "(tool-tagged, git-SHA-stamped) so the BASELINE.md GQA row "
             "is machine-backed like the bench.py extras",
    )
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from oim_tpu.models import TransformerConfig, init_params
    from oim_tpu.models.decode import make_generate_fn

    rtt = measure_rtt(jnp)
    print(
        f"backend={jax.default_backend()} rtt={rtt * 1e3:.0f}ms "
        f"prompt={args.prompt} new={args.new} batch={args.batch}",
        flush=True,
    )

    prompt = (
        jnp.arange(args.batch * args.prompt).reshape(args.batch, args.prompt)
        % 32768
    ).astype(jnp.int32)
    results = {}

    for n_kv in (0, 4, 2):  # 0 = MHA (n_heads kv heads)
        cfg = TransformerConfig(
            vocab_size=args.vocab_size, d_model=args.d_model,
            n_layers=args.n_layers, n_heads=args.n_heads,
            n_kv_heads=n_kv, d_ff=args.d_ff, dtype=args.dtype,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        if args.weights_int8:
            from oim_tpu.ops.quant import quantize_params_int8

            params = quantize_params_int8(params)
        elif args.weights_int4:
            from oim_tpu.ops.quant import quantize_params_int4

            params = quantize_params_int4(params)
        gen = make_generate_fn(cfg)
        for kv_int8 in (False, True):
            out = gen(
                params, prompt, max_new_tokens=args.new, kv_int8=kv_int8
            )
            np.asarray(out)  # compile + materialize
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = gen(
                    params, prompt, max_new_tokens=args.new, kv_int8=kv_int8
                )
            np.asarray(out)
            elapsed = time.perf_counter() - t0
            label = f"GQA-{n_kv}" if n_kv else "MHA"
            kv_label = "int8" if kv_int8 else args.dtype
            if args.weights_int8:
                label += "+w8"
            elif args.weights_int4:
                label += "+w4"
            if elapsed <= rtt:
                # The tunnel readback swamped the measurement; a negative
                # dt would print nonsense tok/s.
                print(
                    f"{label:6s} kv={kv_label}: below noise floor "
                    f"(elapsed {elapsed * 1e3:.0f} ms <= rtt "
                    f"{rtt * 1e3:.0f} ms; raise --iters/--new)",
                    flush=True,
                )
                continue
            dt = (elapsed - rtt) / args.iters
            tok_s = args.batch * args.new / dt
            results[f"{label}_kv_{kv_label}"] = round(tok_s)
            print(
                f"{label:6s} kv={kv_label}: "
                f"{tok_s:8.0f} tok/s  ({dt * 1e3:.0f} ms for "
                f"{args.batch}x{args.new})",
                flush=True,
            )
        del params
    if args.record and results:
        _record(args, rtt, results)
    return 0


def _record(args, rtt: float, results: dict,
            history_path: str | None = None) -> None:
    """Append the matrix to BENCH_HISTORY.jsonl, tool-tagged and
    git-SHA-stamped.  Never raises: the measurements already printed,
    and a missing git binary or read-only checkout must not turn a
    successful benchmark into a non-zero exit."""
    try:
        import json
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if history_path is None:
            history_path = os.path.join(repo, "BENCH_HISTORY.jsonl")
        entry = {
            "tool": "decode_bench",
            "prompt": args.prompt, "new": args.new, "batch": args.batch,
            "tok_per_s": results,
            "tunnel_rtt_ms": round(rtt * 1e3, 1),
            "git_sha": subprocess.run(
                ["git", "rev-parse", "HEAD"],
                capture_output=True, text=True, cwd=repo,
            ).stdout.strip(),
            "timestamp_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }
        with open(history_path, "a") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
        print(f"recorded -> BENCH_HISTORY.jsonl ({len(results)} cells)")
    except Exception as exc:
        print(f"record failed (measurements above still stand): {exc}")


if __name__ == "__main__":
    raise SystemExit(main())
